"""Adaptive overload control (ISSUE 12, doc/robustness.md `Adaptive
overload control`): the measured cost model (jepsen_tpu/calibrate.py),
the self-tuning AIMD ChunkBudget with suspicion-priority scheduling,
the per-stream degradation ladder, and the chaos/soak acceptance test
— sustained overload + injected faults, the service stays live, no
definite violation is missed at any ladder tier, and tier-full
verdicts stay byte-identical to solo runs.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from jepsen_tpu import calibrate, models, service, store, telemetry
from jepsen_tpu.checker import screen, synth, wgl

MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8
FRONTIER = 128
CKPT = 2

TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    from jepsen_tpu import _platform
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


def _canon(x):
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def _strip(d, extra=()):
    return _canon({k: v for k, v in d.items()
                   if k not in TIMING + tuple(extra)})


def _jops(h):
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


def _wgl_spec(**over):
    sp = {"kind": "wgl", "model": service.model_spec(MODEL),
          "chunk-entries": CHUNK, "slots": SLOTS, "engine": "sort",
          "frontier": FRONTIER, "checkpoint-every": CKPT}
    sp.update(over)
    return sp


def _screen_spec():
    return {"kind": "screen", "model": service.model_spec(MODEL)}


def _solo(ops, **kw):
    from jepsen_tpu.checker import streaming
    params = dict(chunk_entries=CHUNK, slots=SLOTS, frontier=FRONTIER,
                  checkpoint_every=CKPT)
    params.update(kw)
    s = streaming.WglStream(MODEL, **params)
    for op in ops:
        s.feed(op)
    return s.finish()


def _counter(name: str) -> float:
    """Total over all label sets of one registry counter (the metrics
    are process-global and cumulative: tests compare deltas)."""
    snap = telemetry.snapshot(compact=True).get(name) or {}
    return sum(v for v in snap.values() if isinstance(v, (int, float)))


def _quiet_service(**kw):
    """A service whose ladder thread never ticks on its own — the
    controller tests drive _ladder_step with synthetic clocks."""
    kw.setdefault("ladder_tick_s", 3600.0)
    return service.VerificationService(**kw)


# ---------------------------------------------------------------------------
# ChunkBudget: AIMD capacity, wakeups, priority, aging
# ---------------------------------------------------------------------------

def test_budget_acquire_release_roundtrip():
    b = service.ChunkBudget(1.0)
    assert b.acquire(0.4, timeout_s=1.0)
    st = b.status()
    assert st["unit"] == "device-seconds"
    assert st["available"] == pytest.approx(0.6)
    b.release(0.4, clean=True, seconds=0.01)
    assert b.status()["available"] == pytest.approx(1.0)


def test_budget_over_capacity_cost_clamps():
    # a single over-budget chunk must always eventually dispatch
    b = service.ChunkBudget(1.0)
    assert b.acquire(50.0, timeout_s=1.0)
    assert b.status()["available"] == pytest.approx(0.0)
    b.release(50.0)
    assert b.status()["available"] == pytest.approx(1.0)


def test_budget_restore_wakes_blocked_waiter():
    """Satellite regression: an acquirer blocked against pre-halve
    capacity must be woken by release()'s notify_all when capacity
    restores — not left to its 100ms poll against a stale snapshot
    (starvation of a cheap stream behind a restored budget)."""
    b = service.ChunkBudget(1.0, hysteresis_s=0.0)
    assert b.acquire(1.0, timeout_s=1.0)      # drain the budget
    got = []

    def waiter():
        got.append(b.acquire(0.5, timeout_s=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    assert not got                            # genuinely blocked
    b.note_oom()                              # capacity halves to 0.5
    t0 = time.monotonic()
    b.release(1.0, clean=True, seconds=0.001)
    t.join(timeout=3.0)
    assert got == [True]
    # woken by the notify, not by a poll-timeout march
    assert time.monotonic() - t0 < 2.0


def test_budget_oom_halves_to_floor():
    b = service.ChunkBudget(1.0)
    for _ in range(20):
        b.note_oom()
    st = b.status()
    assert st["capacity"] == pytest.approx(st["floor"])
    assert st["capacity"] == pytest.approx(
        1.0 * service.BUDGET_FLOOR_FRACTION)
    assert st["ooms"] == 20


def test_budget_latency_blowout_cut_once_per_hysteresis():
    b = service.ChunkBudget(1.0, blowout_s=0.05, hysteresis_s=60.0)
    before = _counter("jepsen_tpu_service_budget_cuts_total")
    for _ in range(12):                       # p95 >> blowout
        b.acquire(0.01, timeout_s=1.0)
        b.release(0.01, clean=True, seconds=1.0)
    st = b.status()
    assert st["cuts"] == 1                    # hysteresis: one cut
    assert st["capacity"] == pytest.approx(0.5)
    assert _counter("jepsen_tpu_service_budget_cuts_total") \
        == before + 1


def test_budget_static_mode_never_latency_cuts():
    b = service.ChunkBudget(1.0, adaptive=False, blowout_s=0.05)
    for _ in range(12):
        b.acquire(0.01, timeout_s=1.0)
        b.release(0.01, clean=True, seconds=1.0)
    st = b.status()
    assert st["cuts"] == 0
    assert st["capacity"] == pytest.approx(1.0)


def test_budget_additive_restore_after_hysteresis():
    b = service.ChunkBudget(1.0, hysteresis_s=0.05, blowout_s=10.0)
    b.note_oom()                              # cut to 0.5
    # inside the hysteresis window: clean chunks do NOT restore
    b.acquire(0.01, timeout_s=1.0)
    b.release(0.01, clean=True, seconds=0.001)
    assert b.status()["capacity"] == pytest.approx(0.5)
    time.sleep(0.08)                          # hysteresis passed
    b.acquire(0.01, timeout_s=1.0)
    b.release(0.01, clean=True, seconds=0.001)
    st = b.status()
    assert st["capacity"] == pytest.approx(
        0.5 + service.BUDGET_RESTORE_STEP)
    # restore is additive and capped at max
    for _ in range(200):
        b.acquire(0.01, timeout_s=1.0)
        b.release(0.01, clean=True, seconds=0.001)
    assert b.status()["capacity"] == pytest.approx(1.0)


def test_budget_restored_capacity_is_spendable():
    """Regression: restore must grow the SPENDABLE pool, not just the
    reported capacity — a stored available-pool clamped at the cut
    conserved the halved budget forever while status() showed max."""
    b = service.ChunkBudget(1.0, hysteresis_s=0.0, blowout_s=10.0)
    b.note_oom()                              # cut to 0.5
    for _ in range(200):                      # additive restore to max
        b.acquire(0.01, timeout_s=1.0)
        b.release(0.01, clean=True, seconds=0.001)
    assert b.status()["capacity"] == pytest.approx(1.0)
    # the restored seconds are actually acquirable in one piece
    assert b.acquire(1.0, timeout_s=1.0)
    b.release(1.0)
    assert b.status()["available"] == pytest.approx(1.0)


def test_budget_mid_latency_restores_at_half_step():
    """Clean chunks between the low-latency bar and half of blowout
    restore at half step — a fleet whose healthy latency sits there
    must not stay halved forever after one OOM."""
    b = service.ChunkBudget(1.0, hysteresis_s=0.0, blowout_s=10.0)
    b.note_oom()
    b.acquire(0.01, timeout_s=1.0)
    b.release(0.01, clean=True, seconds=4.0)  # 0.4x blowout: mid band
    assert b.status()["capacity"] == pytest.approx(
        0.5 + 0.5 * service.BUDGET_RESTORE_STEP)


def test_budget_aged_clean_waiter_blocks_young_suspects():
    """Regression: an aged priority-0 waiter reserves capacity against
    freshly-arriving priority-1 acquirers too — a steady suspect load
    must not starve a clean stream indefinitely."""
    b = service.ChunkBudget(1.0, aging_s=0.2)
    assert b.acquire(0.9, timeout_s=1.0)      # most capacity held
    got_clean = []

    def clean():
        got_clean.append(b.acquire(0.8, timeout_s=10.0, priority=0))

    t = threading.Thread(target=clean)
    t.start()
    time.sleep(0.4)                           # clean waiter aged
    # free room for the suspect but not for the aged clean waiter:
    # the young suspect fits, yet may NOT bypass the reservation
    b.release(0.1, seconds=0.001)
    assert not b.acquire(0.1, timeout_s=0.3, priority=1)
    b.release(0.8, seconds=0.001)             # now the clean one fits
    t.join(timeout=5.0)
    assert got_clean == [True]
    b.release(0.8)


def test_overloaded_ignores_supply_side_signals_without_demand():
    """A lone transient OOM (recent cut, halved capacity) with nobody
    waiting is NOT overload — climbing a clean stream off it would
    turn a deterministic verdict into a deferred one."""
    svc = _quiet_service()
    try:
        calm_after_cut = {"waiters": 0, "capacity": 0.5,
                          "initial": 1.0, "available": 0.5,
                          "p95_latency_s": 0.01,
                          "queue_depth_ewma": 0.0, "recent_cut": True}
        assert not svc.overloaded(calm_after_cut)
        assert svc.overloaded({**calm_after_cut, "waiters": 1})
    finally:
        svc.stop()


def test_status_transitions_counter_survives_worker_reaping():
    """status()['ladder']['transitions'] reads the service-lifetime
    counter, not a sum over (reapable) workers — it must never go
    backwards on a long-lived daemon."""
    svc = _quiet_service()
    try:
        w = svc.admit("s", {"linear": _wgl_spec()})
        assert w.set_tier(service.TIER_SAMPLED, "test")
        assert w.set_tier(service.TIER_FULL, "test")
        assert svc.status()["ladder"]["transitions"] == 2
        with svc._lock:
            svc.workers.clear()               # simulate reaping
        assert svc.status()["ladder"]["transitions"] == 2
    finally:
        svc.stop()


def test_budget_slow_chunks_do_not_restore():
    b = service.ChunkBudget(1.0, hysteresis_s=0.0, blowout_s=10.0)
    b.note_oom()
    b.acquire(0.01, timeout_s=1.0)
    # clean but NOT low-latency: above restore bar (0.25 * blowout)
    b.release(0.01, clean=True, seconds=9.0)
    assert b.status()["capacity"] == pytest.approx(0.5)


def test_budget_hungry_queue_doubles_restore():
    b = service.ChunkBudget(1.0, hysteresis_s=0.0, blowout_s=10.0)
    b.note_oom()
    for _ in range(40):                       # drive the EWMA deep
        b.note_queue_depth(service.BUDGET_HUNGRY_ROWS * 4)
    b.acquire(0.01, timeout_s=1.0)
    b.release(0.01, clean=True, seconds=0.001)
    assert b.status()["capacity"] == pytest.approx(
        0.5 + 2 * service.BUDGET_RESTORE_STEP)


def test_budget_priority_grants_ahead_of_fifo():
    """Suspect streams (priority 1) acquire ahead of clean (priority
    0) waiters that arrived FIRST."""
    b = service.ChunkBudget(1.0)
    assert b.acquire(1.0, timeout_s=1.0)      # saturate
    order = []

    def waiter(tag, prio):
        assert b.acquire(1.0, timeout_s=10.0, priority=prio)
        order.append(tag)
        b.release(1.0, seconds=0.001)

    t_clean = threading.Thread(target=waiter, args=("clean", 0))
    t_clean.start()
    time.sleep(0.15)                          # clean is queued first
    t_susp = threading.Thread(target=waiter, args=("suspect", 1))
    t_susp.start()
    time.sleep(0.15)
    b.release(1.0, seconds=0.001)
    t_susp.join(timeout=5.0)
    t_clean.join(timeout=5.0)
    assert order == ["suspect", "clean"]


def test_budget_aged_waiter_reserves_capacity():
    """Work-conserving bypass flips to reservation once a waiter ages:
    cheap chunks bypass a too-big waiter at first, then capacity is
    reserved so the big waiter cannot starve."""
    b = service.ChunkBudget(1.0, aging_s=0.3)
    assert b.acquire(0.6, timeout_s=1.0)      # avail 0.4
    got_big = []

    def big():
        got_big.append(b.acquire(1.0, timeout_s=10.0))

    t = threading.Thread(target=big)
    t.start()
    time.sleep(0.1)
    # young big waiter: a cheap chunk may still bypass it
    assert b.acquire(0.2, timeout_s=0.5)
    b.release(0.2, seconds=0.001)
    time.sleep(0.4)                           # big waiter aged
    assert not b.acquire(0.2, timeout_s=0.4)  # reserved for the aged
    b.release(0.6, seconds=0.001)             # avail 1.0: big grants
    t.join(timeout=5.0)
    assert got_big == [True]
    b.release(1.0, seconds=0.001)
    assert b.acquire(0.2, timeout_s=1.0)      # and the cheap one too


# ---------------------------------------------------------------------------
# Calibration: the measured cost model
# ---------------------------------------------------------------------------

def test_calibration_converges_to_observed_ratio():
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(50):
        cal.observe("sort", 1e6, 2e-3)        # 2e-9 s/elementop
    assert cal.coeff("sort") == pytest.approx(2e-9, rel=0.05)
    assert cal.count("sort") == 50
    assert cal.seconds("sort", 1e6) == pytest.approx(2e-3, rel=0.05)


def test_calibration_clips_outliers():
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(30):
        cal.observe("sort", 1e6, 1e-3)        # 1e-9 s/elementop
    # one wedged 600s chunk: bounded influence, not a 600000x jump
    cal.observe("sort", 1e6, 600.0)
    assert cal.coeff("sort") < 1e-9 * (1 + calibrate.CLIP_FACTOR)


def test_calibration_ready_gate_and_fallback():
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(calibrate.MIN_OBSERVATIONS - 1):
        cal.observe("dense", 1e6, 1e-3)
    assert not cal.ready("dense")
    cal.observe("dense", 1e6, 1e-3)
    assert cal.ready("dense")
    assert not cal.ready("dense", "sort")     # sort never measured
    # unmeasured variant: geometric-mean fallback, not the nominal
    assert cal.coeff("sort") == pytest.approx(cal.coeff("dense"),
                                              rel=0.01)
    # a cold calibration prices at the nominal constant
    cold = calibrate.Calibration(platform="cpu")
    assert cold.coeff("sort") is None
    assert cold.seconds("sort", 1e9) == pytest.approx(
        1e9 * calibrate.NOMINAL_SECONDS_PER_ELEMENTOP)


def test_calibration_persistence_roundtrip(tmp_path):
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(20):
        cal.observe("sort", 1e6, 1e-3)
        cal.observe("dense", 1e6, 5e-4)
    path = str(tmp_path / "calibration-cpu.json")
    cal.save(path)
    back = calibrate.Calibration.load(path, platform="cpu")
    assert back.coeff("sort") == pytest.approx(cal.coeff("sort"))
    assert back.count("dense") == 20
    assert back.ready("sort", "dense")
    # corrupt file: cold start, never an exception
    with open(path, "w") as fh:
        fh.write("{not json")
    assert calibrate.Calibration.load(path, platform="cpu") \
        .coefficients() == {}
    # platform mismatch: a cpu file must not price a tpu backend
    cal.save(path)
    assert calibrate.Calibration.load(path, platform="tpu") \
        .coefficients() == {}


def test_calibration_missing_file_starts_cold(tmp_path):
    cal = calibrate.Calibration.load(str(tmp_path / "nope.json"),
                                     platform="cpu")
    assert cal.coefficients() == {}


def test_observe_helper_is_noop_without_activation():
    calibrate.deactivate()
    calibrate.observe("sort", 1e6, 1.0)       # must not raise
    assert calibrate.active() is None
    cal = calibrate.activate(calibrate.Calibration(platform="cpu"))
    try:
        calibrate.observe("sort", 1e6, 1e-3)
        assert cal.count("sort") == 1
    finally:
        calibrate.deactivate()


# ---------------------------------------------------------------------------
# select_engine in measured device-seconds
# ---------------------------------------------------------------------------

# a shape the MODELED cost prices dense, and one it prices sort
DENSE_SHAPE = dict(srange=(0, 3), p=4, n=1000)
SORT_SHAPE = dict(srange=(0, 511), p=6, n=200)


def _select(shape, cal=None):
    return wgl.select_engine(shape["srange"], shape["p"], shape["n"],
                             slots=shape["p"], frontier=128,
                             calibration=cal)


def _skewed(bad: str, good: str) -> calibrate.Calibration:
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(calibrate.MIN_OBSERVATIONS + 4):
        cal.observe(bad, 1e6, 1e3)            # measured terrible
        cal.observe(good, 1e6, 1e-6)          # measured great
    return cal


def test_select_engine_uncalibrated_unchanged():
    assert _select(DENSE_SHAPE).family == "dense"
    assert _select(SORT_SHAPE).family == "sort"
    assert _select(DENSE_SHAPE).seconds is None


def test_select_engine_flips_dense_to_sort_on_measurement():
    """The acceptance pin: skewed synthetic latency observations flip
    the engine choice — measured coefficients, not the modeled
    constants, decide."""
    dec = _select(DENSE_SHAPE, _skewed("dense", "sort"))
    assert dec.family == "sort"
    assert "measured" in dec.reason
    assert dec.seconds is not None
    assert dec.seconds["dense"] > dec.seconds["sort"]


def test_select_engine_flips_sort_to_dense_on_measurement():
    dec = _select(SORT_SHAPE, _skewed("sort", "dense"))
    assert dec.family == "dense"
    assert "measured" in dec.reason


def test_select_engine_half_calibrated_never_flips():
    """One noisy variant must not flip a decision: both compared
    variants need MIN_OBSERVATIONS."""
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(calibrate.MIN_OBSERVATIONS + 4):
        cal.observe("dense", 1e6, 1e3)        # only dense measured
    dec = _select(DENSE_SHAPE, cal)
    assert dec.family == "dense"              # modeled decision holds
    assert dec.seconds is None


def test_chunk_cost_prices_in_device_seconds():
    from jepsen_tpu.checker.streaming import WglStream
    s = WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                  frontier=FRONTIER)
    price = service.chunk_cost(s)
    assert isinstance(price, service.ChunkPrice)
    assert price.variant in ("dense", "sort", "hash")
    assert price.cost == pytest.approx(
        price.elementops * calibrate.NOMINAL_SECONDS_PER_ELEMENTOP)
    # calibrated: the same chunk priced at the measured coefficient
    cal = calibrate.Calibration(platform="cpu")
    for _ in range(20):
        cal.observe(price.variant, 1e6, 1e-3)
    cal_price = service.chunk_cost(s, cal)
    assert cal_price.cost == pytest.approx(
        cal_price.elementops * 1e-9, rel=0.1)


# ---------------------------------------------------------------------------
# suspicion propagation: ScreenStream -> worker metadata -> status()
# ---------------------------------------------------------------------------

# value 99 is outside every synth history's 0..4 domain and process
# 900/901 never collide with a generated history's process ids, so
# these four ops turn ANY prefix into a definite phantom-read
PHANTOM_OPS = [
    {"type": "invoke", "f": "write", "value": 1, "process": 900},
    {"type": "ok", "f": "write", "value": 1, "process": 900},
    {"type": "invoke", "f": "read", "value": None, "process": 901},
    {"type": "ok", "f": "read", "value": 99, "process": 901},
]


def _wait(pred, timeout_s=10.0, interval=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_suspicion_flows_from_screen_to_status_and_metrics():
    before = _counter("jepsen_tpu_service_stream_events_total")
    svc = _quiet_service()
    try:
        w = svc.admit("susp", {"screen-linear": _screen_spec()})
        for op in PHANTOM_OPS:
            svc.offer("susp", op)
        assert _wait(lambda: w.suspicion_score
                     >= screen.ESCALATE_THRESHOLD)
        st = svc.status()["streams"]["susp"]
        assert st["suspicion"] >= screen.ESCALATE_THRESHOLD
        assert st["priority"] == 1
        assert st["violation"] is True
        assert w.scheduling_priority() == 1
        # the lifecycle metric counted the prioritization exactly once
        snap = telemetry.snapshot(compact=True)
        events = snap["jepsen_tpu_service_stream_events_total"]
        assert events.get("event=prioritized", 0) >= 1
        assert _counter("jepsen_tpu_service_stream_events_total") \
            > before
        svc.seal("susp")
        r = svc.result("susp", timeout_s=60)
        assert r["screen-linear"]["valid?"] is False
    finally:
        svc.stop()


def test_soft_suspicion_does_not_prioritize():
    """Crashed-mutator soft signals (0.02 each, capped 0.5) ride
    nearly every realistic history — below the escalation bar they
    must not outrank siblings or pin a stream to tier-full."""
    svc = _quiet_service()
    try:
        w = svc.admit("soft", {"screen-linear": _screen_spec()})
        ops = [
            {"type": "invoke", "f": "write", "value": 1, "process": 0},
            {"type": "info", "f": "write", "value": 1, "process": 0},
            {"type": "invoke", "f": "read", "value": None,
             "process": 1},
            {"type": "ok", "f": "read", "value": 1, "process": 1},
        ]
        for op in ops:
            svc.offer("soft", op)
        assert _wait(lambda: w.ops_fed == len(ops))
        w.refresh_suspicion()
        st = svc.status()["streams"]["soft"]
        assert 0 < st["suspicion"] < screen.ESCALATE_THRESHOLD
        assert st["priority"] == 0
        assert w.scheduling_priority() == 0
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------

def test_ladder_climb_and_descend_with_hysteresis():
    """Controller unit test on a synthetic clock: sustained overload
    climbs ONE stream per hold (most expensive first), sustained calm
    descends one per (longer) hold, transitions land in telemetry."""
    before = _counter("jepsen_tpu_service_ladder_transitions_total")
    svc = _quiet_service(ladder_climb_hold_s=1.0,
                         ladder_descend_hold_s=3.0)
    try:
        cheap = svc.admit("cheap", {"linear": _wgl_spec()})
        exp = svc.admit("exp", {"linear": _wgl_spec(
            **{"chunk-entries": 256, "slots": 10})})
        assert exp.device_cost() > cheap.device_cost()

        overloaded = {"waiters": 3, "capacity": 0.1, "initial": 1.0,
                      "available": 0.0, "p95_latency_s": 0.5,
                      "queue_depth_ewma": 0.0, "recent_cut": True}
        calm = {"waiters": 0, "capacity": 1.0, "initial": 1.0,
                "available": 1.0, "p95_latency_s": 0.01,
                "queue_depth_ewma": 0.0, "recent_cut": False}
        assert svc.overloaded(overloaded)
        assert not svc.overloaded(calm)

        svc.budget.signals = lambda: overloaded
        svc._ladder_step(100.0)               # overload onset
        assert exp.current_tier() == service.TIER_FULL
        svc._ladder_step(101.5)               # hold passed: one climb
        assert exp.current_tier() == service.TIER_SAMPLED
        assert cheap.current_tier() == service.TIER_FULL  # ONE climb
        svc._ladder_step(103.0)               # lowest tier first:
        assert cheap.current_tier() == service.TIER_SAMPLED
        svc._ladder_step(104.5)               # then the expensive one
        assert exp.current_tier() == service.TIER_SCREEN

        svc.budget.signals = lambda: calm
        svc._ladder_step(105.0)               # calm onset
        svc._ladder_step(106.5)               # climb hold is NOT
        assert exp.current_tier() == service.TIER_SCREEN  # enough
        svc._ladder_step(108.5)               # descend hold passed:
        assert exp.current_tier() == service.TIER_SAMPLED  # worst 1st
        svc._ladder_step(112.0)               # tie: cheapest first
        assert cheap.current_tier() == service.TIER_FULL
        svc._ladder_step(115.5)
        assert exp.current_tier() == service.TIER_FULL

        assert _counter(
            "jepsen_tpu_service_ladder_transitions_total") \
            == before + 6
        st = svc.status()
        assert st["ladder"]["transitions"] == 6
    finally:
        svc.stop()


def test_ladder_never_climbs_suspect_streams():
    svc = _quiet_service(ladder_climb_hold_s=1.0)
    try:
        suspect = svc.admit("sus", {"linear": _wgl_spec(
            **{"chunk-entries": 256, "slots": 10}),
            "screen-linear": _screen_spec()})
        clean = svc.admit("cln", {"linear": _wgl_spec()})
        for op in PHANTOM_OPS:
            svc.offer("sus", op)
        assert _wait(lambda: suspect.scheduling_priority() == 1)
        svc.budget.signals = lambda: {
            "waiters": 3, "capacity": 0.1, "initial": 1.0,
            "available": 0.0, "p95_latency_s": 0.5,
            "queue_depth_ewma": 0.0, "recent_cut": True}
        svc._ladder_step(100.0)
        svc._ladder_step(101.5)
        # the suspect stream is the expensive one, but it keeps device
        # time; the clean one climbs instead
        assert suspect.current_tier() == service.TIER_FULL
        assert clean.current_tier() == service.TIER_SAMPLED
    finally:
        svc.stop()


def test_ladder_climb_to_shed_is_terminal():
    svc = _quiet_service()
    try:
        w = svc.admit("doomed", {"linear": _wgl_spec()})
        for t in range(service.TIER_FULL + 1, service.TIER_SHED + 1):
            w.set_tier(t, "test")
        assert w.done.wait(10.0)
        assert w.state == service.SHED
        assert "degradation ladder" in w.shed_reason
    finally:
        svc.stop()


def test_screen_only_tier_defers_device_verdict():
    """At screen-only, a clean stream's device verdict defers to
    offline (no 'valid?' key — the checkers' streamed-results reuse
    guard skips it) while its screen verdict is complete; the result
    carries the ladder stamp."""
    ops, _ = _jops(synth.register_history(
        200, concurrency=3, values=5, seed=77)), None
    svc = _quiet_service()
    try:
        w = svc.admit("deg", {"linear": _wgl_spec(),
                              "screen-linear": _screen_spec()})
        w.set_tier(service.TIER_SAMPLED, "test")
        w.set_tier(service.TIER_SCREEN, "test")
        for op in ops:
            svc.offer("deg", op)
        svc.seal("deg")
        r = svc.result("deg", timeout_s=120)
        assert r["linear"]["deferred"] is True
        assert r["linear"]["ladder-tier"] == "screen-only"
        assert "valid?" not in r["linear"]
        assert r["screen-linear"]["valid?"] is True   # screens ran
        assert r["ladder"]["max-tier"] == "screen-only"
        assert r["ladder"]["transitions"] == 2
        # pending chunks were never pumped under the gate
        st = svc.status()["streams"]["deg"]
        assert st["ladder-tier"] == "screen-only"
    finally:
        svc.stop()


def test_screen_only_finish_keeps_already_pumped_verdict():
    """A stream that finished its device work BEFORE the climb keeps
    its verdict: deferral is for unpumped chunks, not for device
    seconds already spent."""
    ops = _jops(synth.register_history(200, concurrency=3, values=5,
                                       seed=76))
    solo = _solo(ops)
    svc = _quiet_service()
    try:
        w = svc.admit("paid", {"linear": _wgl_spec()})
        for op in ops:
            svc.offer("paid", op)
        t = w.targets["linear"]
        assert _wait(lambda: w.ops_fed == len(ops)
                     and t.pending_chunks() == 0)
        w.set_tier(service.TIER_SAMPLED, "test")
        w.set_tier(service.TIER_SCREEN, "test")
        svc.seal("paid")
        r = svc.result("paid", timeout_s=120)
        assert r["linear"]["valid?"] is True      # verdict kept
        assert "deferred" not in r["linear"]
        assert r["ladder"]["max-tier"] == "screen-only"  # stamped
        assert _strip(r["linear"]) == _strip(solo)
    finally:
        svc.stop()


def test_violation_at_screen_only_tier_is_never_missed():
    """The no-missed-violation pin at the worst live tier: a stream
    forced to screen-only turns suspect the moment its screen sees a
    definite violation, descends to full, and its device verdict runs
    after all."""
    valid_ops = _jops(synth.register_history(
        120, concurrency=3, values=5, seed=78))
    svc = _quiet_service()
    try:
        w = svc.admit("v", {"linear": _wgl_spec(),
                            "screen-linear": _screen_spec()})
        w.set_tier(service.TIER_SAMPLED, "test")
        w.set_tier(service.TIER_SCREEN, "test")
        for op in valid_ops:
            svc.offer("v", op)
        for op in PHANTOM_OPS:                # definite violation
            svc.offer("v", op)
        svc.seal("v")
        r = svc.result("v", timeout_s=120)
        # suspicion descended the stream: full device verdict, invalid
        assert r["screen-linear"]["valid?"] is False
        assert r["linear"]["valid?"] is False
        assert "deferred" not in r["linear"]
        assert w.current_tier() == service.TIER_FULL
        assert svc.status()["streams"]["v"]["violation"] is True
    finally:
        svc.stop()


def test_tier_full_verdicts_unstamped_and_byte_identical():
    """Streams that never leave tier-full carry NO ladder stamp —
    byte-identical to solo runs."""
    ops = _jops(synth.register_history(200, concurrency=3, values=5,
                                       seed=79))
    solo = _solo(ops)
    svc = _quiet_service()
    try:
        svc.admit("full", {"linear": _wgl_spec()})
        for op in ops:
            svc.offer("full", op)
        svc.seal("full")
        r = svc.result("full", timeout_s=120)
        assert "ladder" not in r
        assert _strip(r["linear"]) == _strip(solo)
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# the chaos/soak acceptance test
# ---------------------------------------------------------------------------

def test_chaos_overload_faults_service_stays_live(monkeypatch):
    """ISSUE 12 acceptance: sustained overload (budget far below the
    offered load) with injected oom + wedged faults. The service keeps
    answering /healthz-shaped status() and socket verbs, the ladder
    climbs (transitions visible in telemetry), no definite violation
    is missed, and clean streams that stayed at tier-full deliver
    verdicts byte-identical to solo runs."""
    n = 240
    cheap_shape = {}
    exp_shape = {"chunk-entries": 256, "slots": 10}
    hists = {
        "c0": _jops(synth.register_history(n, concurrency=3,
                                           values=5, seed=801)),
        "c1": _jops(synth.register_history(n, concurrency=3,
                                           values=5, seed=802)),
        "e0": _jops(synth.register_history(n, concurrency=3,
                                           values=5, seed=803)),
        "f0": _jops(synth.register_history(n, concurrency=3,
                                           values=5, seed=804)),
        "f1": _jops(synth.register_history(n, concurrency=3,
                                           values=5, seed=805)),
    }
    shapes = {"c0": cheap_shape, "c1": cheap_shape, "e0": exp_shape,
              "f0": cheap_shape, "f1": cheap_shape}
    solos = {name: _solo(ops, **{k.replace("-", "_"): v
                                 for k, v in shapes[name].items()})
             for name, ops in hists.items()}
    # the violation leads the stream: v0 turns suspect on op 4, so
    # suspicion-priority protects it from climbing for the whole storm
    # — the deterministic tier-full stream the byte-identity pin rides
    viol = PHANTOM_OPS + _jops(synth.register_history(
        80, concurrency=3, values=5, seed=806))
    viol_solo = _solo(viol)

    before_climb = _counter(
        "jepsen_tpu_service_ladder_transitions_total")
    monkeypatch.setenv(
        "JEPSEN_TPU_FAULT_INJECT",
        "oom@stream-chunk/f0:2,wedged@stream-chunk/f1:2")
    svc = service.VerificationService(
        budget_elementops=1e5,     # ~every chunk over budget: overload
        adaptive=True,
        ladder_tick_s=0.05,
        ladder_climb_hold_s=0.25,
        ladder_descend_hold_s=0.75)
    bound = svc.serve("127.0.0.1:0")
    try:
        for name in hists:
            svc.admit(name, {"linear": _wgl_spec(**shapes[name]),
                             "screen-linear": _screen_spec()})
        svc.admit("v0", {"linear": _wgl_spec(),
                         "screen-linear": _screen_spec()})

        # liveness probes: the /healthz shape in-process AND the
        # status verb over the real socket, hammered through the storm
        stop = threading.Event()
        probe_lat: list = []
        probe_err: list = []

        def probe():
            try:
                sock = service._connect(bound)
                rf = sock.makefile("r", encoding="utf-8")
                while not stop.is_set():
                    t0 = time.monotonic()
                    sock.sendall(b'{"type": "status", "id": 1}\n')
                    line = rf.readline()
                    st = json.loads(line)["status"]
                    svc.status()              # the /healthz payload
                    probe_lat.append(time.monotonic() - t0)
                    assert st["state"] == "serving"
                    stop.wait(0.05)
                sock.close()
            except Exception as e:  # noqa: BLE001 — surfaced below
                probe_err.append(repr(e))

        prober = threading.Thread(target=probe, daemon=True)
        prober.start()

        results: dict = {}

        def feed(name, ops):
            for op in ops:
                svc.offer(name, op)
            svc.seal(name)
            results[name] = svc.result(name, timeout_s=600)

        feeds = [threading.Thread(target=feed, args=(nm, ops))
                 for nm, ops in list(hists.items()) + [("v0", viol)]]
        for t in feeds:
            t.start()
        for t in feeds:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in feeds), "verb starvation"
        stop.set()
        prober.join(timeout=10)

        # -- liveness: every probe answered, promptly, no errors
        assert not probe_err, probe_err
        assert probe_lat and max(probe_lat) < 5.0
        # -- the ladder climbed under sustained overload, visibly
        assert _counter(
            "jepsen_tpu_service_ladder_transitions_total") \
            > before_climb
        st = svc.status()
        assert st["ladder"]["transitions"] > 0

        # -- no definite violation missed: the suspect stream is
        # priority-protected (never climbed), stays at tier-full, and
        # ends with a full invalid verdict
        assert st["streams"]["v0"]["ladder-max-tier"] == "full"
        assert results["v0"]["screen-linear"]["valid?"] is False
        assert results["v0"]["linear"]["valid?"] is False
        assert "ladder" not in results["v0"]  # tier-full: unstamped
        assert _strip(results["v0"]["linear"]) == _strip(viol_solo)

        # -- every stream delivered SOMETHING sound: a verdict (valid,
        # byte-identical if it stayed at tier-full), a ladder-stamped
        # deferral, or a shed (offline analyze covers it from the
        # journal) — never a wrong verdict, never a hang
        for nm in hists:
            sst = st["streams"][nm]
            r = results[nm]
            if sst["state"] == service.SHED:
                continue   # shed-to-offline: the pre-existing rung
            lin = r["linear"]
            if lin.get("deferred"):
                assert lin["ladder-tier"]      # stamped deferral
                assert "valid?" not in lin
                continue
            assert lin["valid?"] is True, (nm, lin)
            if sst["ladder-max-tier"] == "full" \
                    and nm not in ("f0", "f1"):  # faulted: recovery
                assert "ladder" not in r         # trail rides result
                assert _strip(lin) == _strip(solos[nm]), nm
            elif sst["ladder-max-tier"] != "full":
                assert "ladder" in r, nm         # degraded: stamped

        # -- calibration observed real chunks through the storm
        coeffs = st["calibration"]["coefficients"]
        assert coeffs.get("sort", {}).get("observations", 0) > 0
    finally:
        stop.set()
        svc.stop()


def test_drain_persists_calibration(tmp_path):
    svc = _quiet_service()
    path = str(tmp_path / "calibration-cpu.json")
    svc.calibration_path = path
    for _ in range(20):
        svc.calibration.observe("sort", 1e6, 1e-3)
    svc.drain(timeout_s=10)
    svc.stop()
    back = calibrate.Calibration.load(path, platform=None)
    assert back.count("sort") == 20


def test_service_status_cli_renders(capsys):
    from jepsen_tpu import cli
    svc = _quiet_service()
    try:
        bound = svc.serve("127.0.0.1:0")
        svc.admit("s0", {"screen-linear": _screen_spec()})
        assert cli._service_status(bound) == 0
        out = capsys.readouterr().out
        assert "service serving" in out
        assert "s0" in out
        assert "tier=full" in out
        assert "budget:" in out
        assert "calibration" in out
    finally:
        svc.stop()


def test_report_lines_surface_ladder():
    from jepsen_tpu import report
    line = report.service_line({
        "state": "serving",
        "streams": {"a": {"state": "streaming",
                          "ladder-tier": "screen-only"},
                    "b": {"state": "streaming",
                          "ladder-tier": "full"}},
        "budget": {"initial": 1.0, "capacity": 0.25, "ooms": 1,
                   "cuts": 3},
        "ladder": {"transitions": 5}})
    assert "1 ladder-degraded" in line
    assert "3 AIMD cuts" in line
    assert "5 ladder transitions" in line
    # older status dicts (pre-ladder) still render
    legacy = report.service_line({
        "state": "serving",
        "streams": {"a": {"state": "verdict"}},
        "budget": {"initial": 1e9, "capacity": 5e8, "ooms": 1}})
    assert "1 OOM backpressure events" in legacy
    assert "ladder" not in legacy

    tline = report.telemetry_line({
        "linear": {"deferred": True, "ladder-tier": "screen-only",
                   "history-len": 10},
        "ladder": {"tier": "screen-only", "max-tier": "screen-only",
                   "transitions": 2}})
    assert "ladder tier screen-only" in tline
    assert "1 device verdict deferred" in tline
    # older results without the fields stay silent
    assert report.telemetry_line({"valid?": True}) == ""
