"""In-process RESP-protocol servers: a fake Disque (ADDJOB/GETJOB/
ACKJOB) and a fake Redis-like register (GET/SET), standing in for the
real systems in hermetic suite tests, the reference's dummy tier."""

from __future__ import annotations

import collections
import itertools
import socketserver

from netutil import NodelayHandler
import threading


def _encode(v) -> bytes:
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, int):
        return b":%d\r\n" % v
    if isinstance(v, Exception):
        return b"-ERR %s\r\n" % str(v).encode()
    if isinstance(v, (list, tuple)):
        return b"*%d\r\n" % len(v) + b"".join(_encode(x) for x in v)
    b = v if isinstance(v, bytes) else str(v).encode()
    return b"$%d\r\n%s\r\n" % (len(b), b)


class _RESPHandler(NodelayHandler):

    def handle(self):
        buf = b""
        while True:
            while b"\r\n" not in buf:
                chunk = self.request.recv(65536)
                if not chunk:
                    return
                buf += chunk
            # parse an array of bulk strings
            try:
                line, buf = buf.split(b"\r\n", 1)
                n = int(line[1:])
                args = []
                for _ in range(n):
                    while b"\r\n" not in buf:
                        buf += self.request.recv(65536)
                    ln, buf = buf.split(b"\r\n", 1)
                    size = int(ln[1:])
                    while len(buf) < size + 2:
                        buf += self.request.recv(65536)
                    args.append(buf[:size].decode())
                    buf = buf[size + 2:]
            except (ValueError, IndexError):
                return
            srv = self.server
            if srv.fail_hook:
                err = srv.fail_hook(args)
                if err:
                    self.request.sendall(b"-ERR %s\r\n" % err.encode())
                    continue
            reply = srv.dispatch(args)
            self.request.sendall(_encode(reply))


class _Base(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _RESPHandler)
        self.port = self.server_address[1]
        self.fail_hook = None  # fail_hook(args) -> error str | None
        self.lock = threading.Lock()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()


class FakeDisque(_Base):
    """ADDJOB queue body timeout [...] / GETJOB ... FROM q / ACKJOB id."""

    def __init__(self):
        self.queues: dict = collections.defaultdict(collections.deque)
        self.unacked: dict = {}
        self.ids = itertools.count(1)
        super().__init__()

    def dispatch(self, args):
        cmd = args[0].upper()
        with self.lock:
            if cmd == "ADDJOB":
                q, body = args[1], args[2]
                jid = f"D-{next(self.ids):08x}"
                self.queues[q].append((jid, body))
                return jid
            if cmd == "GETJOB":
                # GETJOB TIMEOUT ms COUNT n FROM q...
                qs = args[args.index("FROM") + 1:]
                for q in qs:
                    if self.queues[q]:
                        jid, body = self.queues[q].popleft()
                        self.unacked[jid] = (q, body)
                        return [[q, jid, body]]
                return None
            if cmd == "ACKJOB":
                self.unacked.pop(args[1], None)
                return 1
            if cmd == "CLUSTER":
                return "OK"
        return Exception(f"unknown command {cmd}")

    def requeue_unacked(self):
        """Simulate retry delivery of every un-acked job."""
        with self.lock:
            for jid, (q, body) in self.unacked.items():
                self.queues[q].append((jid, body))
            self.unacked.clear()


class FakeRedis(_Base):
    """GET/SET register (raftis-style)."""

    def __init__(self):
        self.data: dict = {}
        super().__init__()

    def dispatch(self, args):
        cmd = args[0].upper()
        with self.lock:
            if cmd == "GET":
                return self.data.get(args[1])
            if cmd == "SET":
                self.data[args[1]] = args[2]
                return "OK"
        return Exception(f"unknown command {cmd}")
