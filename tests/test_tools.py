"""Smoke tests for the repo's measurement tools (tools/*.py): each must
run standalone on the CPU platform and emit one parseable JSON line —
the same contract bench.py has with the driver."""

import json
import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def _run(args, timeout=240):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run([sys.executable, *args], cwd=ROOT, env=env,
                       capture_output=True, text=True, timeout=timeout)
    assert p.returncode == 0, p.stderr[-2000:]
    return json.loads(p.stdout.strip().splitlines()[-1])


def test_scale_probe_smoke():
    out = _run(["tools/scale_probe.py", "--n", "1500", "--budget", "120"])
    assert out["valid"] is True and out["solved_in_budget"] is True
    assert out["n_ops"] == 1500 and out["ops_per_s"] > 0
    assert out["analyzer"].startswith("tpu-wgl")


def test_profile_elle_smoke():
    out = _run(["tools/profile_elle.py", "--n", "2000", "--repeat", "2"])
    assert out["n_txns"] == 2000
    assert set(out["phases"]) >= {"graph_build_s", "device_scc_closure_s"}
    assert out["txns_per_s_best"] > 0
