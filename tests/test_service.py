"""The persistent verification service (jepsen_tpu/service.py): multi-
stream multiplexing, per-stream fault isolation, admission control +
shed, SIGTERM drain + checkpoint resume, the socket protocol, and the
store satellites (synchronous Journal unsubscribe, JournalTail idle
backoff, resume manifests).

The isolation contract under test (ISSUE 8 acceptance): with N
concurrent streams and one injected fault, the siblings' verdicts,
frontiers, and blame certificates are byte-identical (as canonical
JSON — every op rides the journal's JSON encoding either way) to solo
runs, the faulted stream resumes via its own checkpoint, and a
SIGTERM drain + restart produces verdicts identical to an
uninterrupted service.
"""

from __future__ import annotations

import gzip
import json
import os
import signal
import threading
import time

import pytest

from jepsen_tpu import models, service, store
from jepsen_tpu.checker import streaming, synth

MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8        # sized so no history rebuilds mid-stream (a rebuild
FRONTIER = 128   # would make attested tallies feed-timing-dependent)
CKPT = 2         # and small enough that the CPU sort kernel is fast

# keys whose values are process/feed-timing diagnostics, not verdict
# content ('violation-at-op' counts ops *fed* at detection — a
# scheduler-timing artifact in a service; the blame certificate
# itself is deterministic and IS compared)
TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    """The kind@site:n injection counters are process-global and keyed
    by site; each test's clauses must count from zero."""
    from jepsen_tpu import _platform
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


def _canon(x):
    """Canonical JSON form — 'byte-identical' means identical once
    serialized the way the journal/results serialize everything."""
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def _strip(d, extra=()):
    return _canon({k: v for k, v in d.items()
                   if k not in TIMING + tuple(extra)})


def _jops(h):
    """History ops as the journal would deliver them (JSON round-trip:
    tuples become lists — the wire form both solo and service feeds
    must share for byte-identity)."""
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


def _solo(ops, **kw):
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT,
                            **kw)
    for op in ops:
        s.feed(op)
    return s.finish()


_HISTS: dict = {}


def _hist(seed, n=300, corrupt_seed=None):
    """Deterministic journal-form history + its solo verdict, cached
    across tests (the fault matrix reuses the same siblings for every
    fault kind)."""
    key = (seed, n, corrupt_seed)
    if key not in _HISTS:
        h = synth.register_history(n, concurrency=3, values=5,
                                   seed=seed)
        if corrupt_seed is not None:
            h = synth.corrupt(h, seed=corrupt_seed)
        ops = _jops(h)
        _HISTS[key] = (ops, _solo(ops))
    return _HISTS[key]


def _wgl_spec(**over):
    sp = {"kind": "wgl", "model": service.model_spec(MODEL),
          "chunk-entries": CHUNK, "slots": SLOTS, "engine": "sort",
          "frontier": FRONTIER, "checkpoint-every": CKPT}
    sp.update(over)
    return sp


def _write_journal(run_dir, ops):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "journal.jsonl"), "w") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default) + "\n")


def _write_history_gz(run_dir, ops):
    with gzip.open(os.path.join(run_dir, "history.jsonl.gz"),
                   "wt") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default) + "\n")


# -- store satellites -------------------------------------------------------

def test_journal_unsubscribe_is_synchronous(tmp_path):
    """The pinned race: unsubscribing while append is mid-notify used
    to deliver one late callback after unsubscribe returned. Now
    unsubscribe blocks until the in-flight delivery completes, and
    nothing is delivered afterwards."""
    j = store.Journal(str(tmp_path / "j.jsonl"))
    received = []
    in_notify = threading.Event()
    gate = threading.Event()

    def fn(op):
        received.append(op)
        in_notify.set()
        gate.wait(5.0)

    unsub = j.subscribe(fn)
    t = threading.Thread(
        target=lambda: j.append({"type": "invoke", "process": 0}))
    t.start()
    assert in_notify.wait(5.0)
    # delivery is in flight: unsubscribe must BLOCK, not return with
    # the callback still running
    u = threading.Thread(target=unsub)
    u.start()
    u.join(0.2)
    assert u.is_alive(), "unsubscribe returned mid-delivery"
    gate.set()
    u.join(5.0)
    assert not u.is_alive()
    t.join(5.0)
    # after unsubscribe returns, no further delivery — ever
    j.append({"type": "ok", "process": 0})
    assert len(received) == 1
    j.close()


def test_journal_unsubscribe_from_callback(tmp_path):
    """A callback unsubscribing a later subscriber in the same notify
    batch suppresses its delivery (and must not deadlock)."""
    j = store.Journal(str(tmp_path / "j.jsonl"))
    got_b = []
    unsub_b_box = []

    def a(op):
        unsub_b_box[0]()

    def b(op):
        got_b.append(op)

    j.subscribe(a)
    unsub_b_box.append(j.subscribe(b))
    j.append({"type": "invoke", "process": 0})
    assert got_b == []
    j.close()


def test_journal_tail_idle_backoff(tmp_path):
    import random

    p = str(tmp_path / "j.jsonl")
    tail = store.JournalTail(p, idle_base_s=0.05, idle_cap_s=1.0,
                             rng=random.Random(7))
    assert tail.idle_s == 0.0
    # empty polls back off (decorrelated jitter within [base, cap])
    delays = []
    for _ in range(8):
        assert tail.poll() == []
        delays.append(tail.idle_s)
    assert all(0.05 <= d <= 1.0 for d in delays)
    assert max(delays) > 0.05          # it actually grew
    # data resets the schedule to zero
    with open(p, "w") as fh:
        fh.write('{"type": "invoke", "process": 0}\n')
    assert len(tail.poll()) == 1
    assert tail.idle_s == 0.0
    # a torn tail means the writer is mid-line: NOT idle
    with open(p, "a") as fh:
        fh.write('{"type": "ok", "pro')
    assert tail.poll() == []
    assert tail.idle_s == 0.0
    # quiet again: the backoff restarts from base
    assert tail.poll() == []
    assert tail.idle_s == 0.05


def test_resume_manifest_roundtrip(tmp_path):
    import numpy as np

    d = str(tmp_path / "run")
    man = {"stream": "s1", "targets": {"linear": _wgl_spec()},
           "ops-fed": 42,
           "checkpoints": {"linear": {
               "rows": 128, "chunks": 2, "p": 16,
               "carry": [np.arange(6, dtype=np.int32),
                         np.ones((2, 3), np.int32)]}}}
    store.write_service_resume(d, man)
    back = store.load_service_resume(d)
    assert back["stream"] == "s1"
    assert back["ops-fed"] == 42
    ck = back["checkpoints"]["linear"]
    assert ck["rows"] == 128 and ck["p"] == 16
    assert (ck["carry"][0] == np.arange(6)).all()
    assert (ck["carry"][1] == np.ones((2, 3))).all()
    store.clear_service_resume(d)
    assert store.load_service_resume(d) is None


def test_streamed_results_flush_and_load_test(tmp_path):
    d = str(tmp_path / "store" / "t" / "20260101T000000")
    h = synth.register_history(40, concurrency=3, values=3, seed=1)
    _write_journal(d, _jops(h))
    store.write_streamed_results(d, {"linear": {"valid?": True,
                                                "streamed": True}})
    t = store.load_test(d)
    assert t["streamed-results"]["linear"]["valid?"] is True


# -- spec round-trips -------------------------------------------------------

def test_model_spec_roundtrip():
    for m in (models.cas_register(), models.cas_register(0),
              models.register(3)):
        assert service.model_from_spec(
            _canon(service.model_spec(m))) == m


def test_targets_spec_walks_checkers():
    from jepsen_tpu.checker import linearizable

    t = {"checker": linearizable(models.cas_register(0)),
         "concurrency": 5, "online-chunk-entries": 128}
    spec = service.targets_spec(t)
    assert set(spec) == {"linear"}
    assert spec["linear"]["kind"] == "wgl"
    assert spec["linear"]["chunk-entries"] == 128
    # tier screen adds the live screen target
    t["tier"] = "screen"
    spec = _canon(service.targets_spec(t))
    assert set(spec) == {"linear", "screen-linear"}
    # and the spec survives the wire (JSON) back into live targets
    targets = service.build_targets(spec, stream_name="x")
    assert targets["linear"].fault_site == "stream-chunk/x"
    assert targets["linear"].auto_pump is False


def test_external_pump_parity():
    """auto_pump=False + manual pump() == the auto-pumped stream."""
    ops = _jops(synth.register_history(300, concurrency=4, values=5,
                                       seed=21))
    auto = _solo(ops)
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT,
                            auto_pump=False)
    for op in ops:
        s.feed(op)
    assert s.pending_chunks() > 0
    while s.pending_chunks():
        assert s.pump(1) == 1
    r = s.finish()
    assert _strip(r) == _strip(auto)


# -- multiplexing + isolation ----------------------------------------------

def _run_streams(svc, hists):
    """Feed each history concurrently through its own stream."""
    for n in hists:
        svc.admit(n, {"linear": _wgl_spec()})

    def feed(n):
        for op in hists[n]:
            svc.offer(n, op)
        svc.seal(n)

    ths = [threading.Thread(target=feed, args=(n,)) for n in hists]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    return {n: svc.result(n, timeout_s=300) for n in hists}


def test_service_multiplexes_and_matches_solo():
    hists = {"a": _hist(31)[0], "b": _hist(32, corrupt_seed=5)[0]}
    solos = {"a": _hist(31)[1], "b": _hist(32, corrupt_seed=5)[1]}
    svc = service.VerificationService()
    res = _run_streams(svc, hists)
    assert solos["a"]["valid?"] is True
    assert solos["b"]["valid?"] is False
    for n in hists:
        assert _strip(res[n]["linear"]) == _strip(solos[n]), n
    st = svc.status()
    assert st["state"] == "serving"
    assert all(s["state"] == "verdict"
               for s in st["streams"].values())
    assert st["budget"]["capacity"] == st["budget"]["initial"]


@pytest.mark.parametrize("kind,clause", [
    ("oom", "oom@stream-chunk/r2:3"),
    ("device-lost", "device-lost@stream-chunk/r2:3"),
    ("wedged", "wedged@stream-chunk/r2:3"),
    ("bitflip", "bitflip@stream-chunk/r2:2"),
])
def test_service_isolation_fault_matrix(kind, clause, monkeypatch):
    """ISSUE 8 acceptance: 4 concurrent streams, one injected fault on
    r2 (per-stream fault site). The 3 siblings — including an invalid
    one, so blame certificates are compared — are byte-identical to
    solo runs; r2 recovers through its own ladder/checkpoint and its
    verdict (minus the recovery/attest trail) matches its solo run
    too."""
    seeds = {"r0": (40, None), "r1": (41, 9), "r2": (42, None),
             "r3": (43, None)}   # r1 invalid: blame must be untouched
    hists = {n: _hist(sd, corrupt_seed=c)[0]
             for n, (sd, c) in seeds.items()}
    solos = {n: _hist(sd, corrupt_seed=c)[1]
             for n, (sd, c) in seeds.items()}
    assert solos["r1"]["valid?"] is False
    assert "op" in solos["r1"]          # the blame certificate

    monkeypatch.setenv("JEPSEN_TPU_FAULT_INJECT", clause)
    svc = service.VerificationService()
    res = _run_streams(svc, hists)
    monkeypatch.delenv("JEPSEN_TPU_FAULT_INJECT")

    for n in ("r0", "r1", "r3"):        # siblings: full byte-identity
        assert _strip(res[n]["linear"]) == _strip(solos[n]), n
    r2 = res["r2"]["linear"]
    rec = r2.get("recovered")
    assert isinstance(rec, dict), f"r2 did not recover: {r2}"
    want = "corrupt" if kind == "bitflip" else kind
    assert want in rec["faults"]
    assert rec.get("resumed-from-chunk") is not None
    # the faulted stream's verdict still matches its solo run
    assert _strip(r2, ("recovered", "attested")) == \
        _strip(solos["r2"], ("recovered", "attested"))
    st = svc.status()["streams"]["r2"]
    assert st["recoveries"] >= 1
    if kind == "bitflip":
        assert st["attest-failures"] >= 1
    if kind == "oom":
        b = svc.status()["budget"]
        assert b["ooms"] == 1
        assert b["capacity"] < b["initial"]


def test_service_quarantine_contains_unclassified(tmp_path):
    """A checker bug (unclassified exception) quarantines ONLY its
    stream — degraded with the error attached — while a sibling runs
    to a clean verdict."""
    good = _hist(42)[0]
    bad = _hist(43)[0]
    svc = service.VerificationService()
    wb = svc.admit("bad", {"linear": _wgl_spec()})
    svc.admit("good", {"linear": _wgl_spec()})

    def boom(max_chunks=None):
        raise TypeError("checker bug, not a device fault")

    wb.targets["linear"].pump = boom
    for n, ops in (("bad", bad), ("good", good)):
        for op in ops:
            svc.offer(n, op)
        svc.seal(n)
    rb = svc.result("bad", timeout_s=60)
    rg = svc.result("good", timeout_s=300)
    assert rb.get("degraded") is True
    assert "checker bug" in rb.get("error", "")
    assert rg["linear"]["valid?"] is True
    st = svc.status()
    assert st["streams"]["bad"]["state"] == "quarantined"
    assert st["streams"]["good"]["state"] == "verdict"
    assert st["quarantined"] == ["bad"]


def test_service_shed_backpressure_deferred(tmp_path, monkeypatch):
    """A stream whose bounded queue stays full past shed_timeout_s is
    shed: deferred marker in its run dir, empty results (offline
    analyze covers it from the journal), siblings unaffected."""
    run_dir = str(tmp_path / "store" / "shed" / "t0")
    os.makedirs(run_dir)
    svc = service.VerificationService(queue_ops=4,
                                      shed_timeout_s=0.3)
    w = svc.admit("slow", {"linear": _wgl_spec()},
                  store_dir=run_dir)
    # wedge the worker so the queue cannot drain
    monkeypatch.setattr(
        w, "_feed", lambda op: time.sleep(30))
    ops = _hist(61)[0]
    shed = False
    for op in ops:
        if not svc.offer("slow", op):
            shed = True
            break
    assert shed
    assert w.state == service.SHED
    assert svc.result("slow", timeout_s=10) == {}
    sr = store.load_streamed_results(run_dir)
    assert sr["deferred"] is True
    assert "backpressure" in sr["reason"]
    # a sibling admitted after the shed still verifies cleanly
    good = _hist(62)[0]
    svc.admit("fine", {"linear": _wgl_spec()})
    for op in good:
        svc.offer("fine", op)
    svc.seal("fine")
    assert svc.result("fine", timeout_s=300)["linear"]["valid?"] \
        is True


def test_service_admission_control():
    svc = service.VerificationService(max_streams=1)
    svc.admit("only", {"linear": _wgl_spec()})
    with pytest.raises(service.AdmissionRefused):
        svc.admit("more", {"linear": _wgl_spec()})
    with pytest.raises(service.AdmissionRefused):
        svc.admit("only", {"linear": _wgl_spec()})   # name collision
    assert svc.status()["refused-total"] == 2
    svc.drain()
    with pytest.raises(service.AdmissionRefused):
        svc.admit("late", {"linear": _wgl_spec()})


def test_concurrent_drain_does_not_hold_service_lock():
    """A second drain() must wait for the first OUTSIDE _lock: every
    service verb's worker lookup takes _lock, so waiting under it
    would freeze offer/poll/status (incl. /healthz) for timeout_s."""
    svc = service.VerificationService()
    with svc._lock:
        svc.draining = True     # simulate a first drainer in flight
    t = threading.Thread(target=svc.drain, kwargs={"timeout_s": 2.0},
                         daemon=True)
    t.start()
    time.sleep(0.1)             # let it reach the wait
    t0 = time.monotonic()
    st = svc.status()
    took = time.monotonic() - t0
    assert st["state"] == "draining"
    assert took < 0.5, f"status() blocked {took:.2f}s behind drain()"
    svc.drained.set()
    t.join(timeout=5)
    assert not t.is_alive()


# -- drain + resume ---------------------------------------------------------

@pytest.mark.parametrize("seed,corrupt", [(73, False), (74, True)])
def test_sigterm_drain_then_resume_identical(tmp_path, seed, corrupt):
    """ISSUE 8 acceptance: SIGTERM mid-stream, then a fresh service
    resumes from the carry checkpoint manifest to a verdict identical
    to an uninterrupted run's — for a valid and an invalid (blame)
    history."""
    ops, solo = _hist(seed, n=600, corrupt_seed=3 if corrupt else None)

    run_dir = str(tmp_path / "store" / "drain" / "t0")
    _write_journal(run_dir, ops)
    svc = service.VerificationService()
    old = signal.getsignal(signal.SIGTERM)
    try:
        svc.install_sigterm()
        svc.admit("t0", {"linear": _wgl_spec()}, store_dir=run_dir)
        for op in ops[:len(ops) // 2]:
            svc.offer("t0", op)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            ck = svc.workers["t0"].targets["linear"]._ckpt
            if ck is not None and svc.workers["t0"].q.empty():
                break
            time.sleep(0.02)
        os.kill(os.getpid(), signal.SIGTERM)   # handler drains
        assert svc.drained.wait(60)
    finally:
        signal.signal(signal.SIGTERM, old)
    assert svc.status()["streams"]["t0"]["state"] == service.DRAINED
    man = store.load_service_resume(run_dir)
    assert man is not None
    ck = man["checkpoints"]["linear"]
    assert ck["chunks"] >= 1           # a real carry checkpoint

    # restart: the (now complete) journal re-feeds; dispatch resumes
    # from the checkpoint instead of recomputing the prefix
    _write_history_gz(run_dir, ops)
    svc2 = service.VerificationService()
    name = svc2.resume(run_dir)
    assert name == "t0"
    r = svc2.result(name, timeout_s=300)
    assert _strip(r["linear"]) == _strip(solo)
    st = svc2.status()["streams"][name]["chunks"]["linear"]
    assert st["resumed-from-chunk"] == ck["chunks"]
    # the prefix really was skipped: fewer live chunk syncs than a
    # cold run would pay
    assert st["chunk-syncs"] < solo["chunks"]
    # the manifest is consumed and the verdicts are flushed for
    # analyze/load_test pickup
    assert store.load_service_resume(run_dir) is None
    assert store.load_test(run_dir)["streamed-results"]["linear"][
        "valid?"] == r["linear"]["valid?"]


def test_watch_admits_tails_and_seals(tmp_path):
    """Store watching: a run dir with a live journal is admitted via
    spec_fn, tailed with idle backoff, and sealed to a verdict once
    history.jsonl.gz lands."""
    base = str(tmp_path / "store")
    run_dir = os.path.join(base, "watched", "t1")
    ops, solo = _hist(81)
    _write_journal(run_dir, ops[:100])

    svc = service.VerificationService()
    svc.watch(base, spec_fn=lambda d: {"linear": _wgl_spec()},
              scan_interval_s=0.05)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not svc.workers:
        time.sleep(0.02)
    assert svc.workers, "watcher never admitted the run"
    name = next(iter(svc.workers))
    # append the rest of the journal live, then finish the run
    with open(os.path.join(run_dir, "journal.jsonl"), "a") as fh:
        for op in ops[100:]:
            fh.write(json.dumps(op, default=store._json_default)
                     + "\n")
    _write_history_gz(run_dir, ops)
    r = svc.result(name, timeout_s=300)
    assert _strip(r["linear"]) == _strip(solo)
    assert store.load_streamed_results(run_dir)["linear"]["valid?"] \
        is True
    svc.stop()


# -- the socket layer -------------------------------------------------------

def test_socket_protocol_and_status(tmp_path):
    import socket as _socket

    ops, solo = _hist(91)
    svc = service.VerificationService()
    addr = svc.serve("127.0.0.1:0")
    host, _, port = addr.rpartition(":")
    conn = _socket.create_connection((host, int(port)))
    rf = conn.makefile("r")

    def req(msg):
        conn.sendall((json.dumps(msg) + "\n").encode())

    req({"type": "attach", "stream": "s1",
         "targets": {"linear": _wgl_spec()}, "id": 1})
    assert json.loads(rf.readline())["ok"] is True
    for op in ops:
        req({"type": "op", "op": op})
    req({"type": "poll", "id": 2})
    assert json.loads(rf.readline())["violation"] is False
    req({"type": "status", "id": 3})
    st = json.loads(rf.readline())["status"]
    assert "s1" in st["streams"]
    req({"type": "finish", "id": 4})
    fin = json.loads(rf.readline())
    assert fin["state"] == service.VERDICT
    assert _strip(fin["results"]["linear"]) == _strip(solo)
    conn.close()
    svc.stop()


def test_service_client_abort_on_violation():
    bad = _jops(synth.register_history(2000, concurrency=3, values=5,
                                       seed=92))
    # make an early read impossible (99 is never written): the stream
    # confirms a dead frontier within a few chunks, long before the
    # feed ends
    for op in bad[200:]:
        if op.get("type") == "ok" and op.get("f") == "read":
            op["value"] = 99
            break
    svc = service.VerificationService()
    addr = svc.serve("127.0.0.1:0")
    t = {"name": "abort", "start-time": "now",
         "abort-on-violation": True, "store-dir": None}
    c = service.ServiceClient(addr, t,
                              spec={"linear": _wgl_spec()})
    aborted = False
    for op in bad:
        c.offer(op)
        if c.should_abort():
            aborted = True
            break
        time.sleep(0.0005)
    # the violation may confirm on a chunk boundary after the feed
    # loop drained — keep polling like the interpreter would
    deadline = time.monotonic() + 30
    while not aborted and time.monotonic() < deadline:
        aborted = c.should_abort()
        time.sleep(0.05)
    assert aborted, "violation never surfaced through poll"
    c.close()
    svc.stop()


def test_refused_attach_falls_back(tmp_path):
    svc = service.VerificationService(max_streams=0)
    addr = svc.serve("127.0.0.1:0")
    from jepsen_tpu.checker import linearizable
    t = {"name": "x", "start-time": "t", "service": addr,
         "checker": linearizable(models.cas_register(0)),
         "concurrency": 4}
    assert service.maybe_attach(t) is None   # refused, no raise
    t["service"] = "127.0.0.1:1"             # nothing listens here
    assert service.maybe_attach(t) is None   # unreachable, no raise
    svc.stop()


# -- CI smoke: two concurrent fake-etcd runs over a real socket -------------

def test_two_concurrent_fake_etcd_runs_through_service(tmp_path):
    import random

    from fake_etcd import FakeEtcd

    import jepsen_tpu.db
    import jepsen_tpu.os_
    from jepsen_tpu import core, generator as gen
    from jepsen_tpu.checker import linearizable
    from jepsen_tpu.suites import etcd

    svc = service.VerificationService()
    addr = svc.serve("127.0.0.1:0")

    fakes = [FakeEtcd(), FakeEtcd()]
    for f in fakes:
        f.port = f.start()

    def make_test(i, fake):
        rng = random.Random(1000 + i)
        return {
            "name": f"etcd-service-smoke-{i}",
            "nodes": ["n1", "n2", "n3"],
            "ssh": {"dummy": True},
            "db": jepsen_tpu.db.noop,
            "os": jepsen_tpu.os_.noop,
            "client": etcd.EtcdClient(),
            "client-url-fn":
                lambda node: f"http://127.0.0.1:{fake.port}",
            "concurrency": 4,
            "store-dir": str(tmp_path / "store"),
            # single-register mode: scalar values land on key 'r'
            "checker": linearizable(models.cas_register()),
            "service": addr,
            "online-chunk-entries": CHUNK,
            "generator": gen.clients(gen.limit(150, gen.mix([
                lambda: {"f": "read"},
                lambda: {"f": "write",
                         "value": rng.randint(0, 4)},
                lambda: {"f": "cas",
                         "value": [rng.randint(0, 4),
                                   rng.randint(0, 4)]},
            ]))),
        }

    done: dict = {}

    def run_one(i, fake):
        done[i] = core.run(make_test(i, fake))

    ths = [threading.Thread(target=run_one, args=(i, f))
           for i, f in enumerate(fakes)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(300)
    for f in fakes:
        f.stop()
    assert sorted(done) == [0, 1]
    for i in (0, 1):
        res = done[i]["results"]
        assert res["valid?"] is True, res
        # the verdict came from the service stream, not an offline
        # re-check
        assert res.get("streamed") is True
        assert done[i]["streamed-results"]["linear"]["valid?"] is True
    st = svc.status()
    assert len(st["streams"]) == 2
    assert all(s["state"] == service.VERDICT
               for s in st["streams"].values())
    svc.stop()


# -- CLI / surfacing --------------------------------------------------------

def test_cli_has_service_command_and_option():
    from jepsen_tpu import cli

    cmds = cli.service_cmd()
    assert "service" in cmds
    longs = [o["long"] for o in cmds["service"]["opt_spec"]]
    assert "--bind" in longs and "--watch" in longs
    assert any(o["long"] == "--service"
               for o in cli.test_opt_spec())


def test_report_service_line_and_web_note(tmp_path):
    from jepsen_tpu import report, web

    line = report.service_line({
        "state": "serving",
        "streams": {"a": {"state": "streaming"},
                    "b": {"state": "verdict"}},
        "budget": {"initial": 1e9, "capacity": 5e8, "ooms": 1}})
    assert "1 streaming" in line and "1 verdict" in line
    assert "OOM" in line
    assert report.service_line({}) == ""
    # web: a shed run surfaces its deferred marker on the index
    base = str(tmp_path / "store")
    d = os.path.join(base, "shedded", "t0")
    os.makedirs(d)
    store.write_streamed_results(d, {"deferred": True,
                                     "reason": "backpressure"})
    rows = web.fast_tests(base)
    assert rows[0]["results"]["service"] == "deferred"
    assert "(service: deferred)" in web.recovery_note(
        rows[0]["results"])
