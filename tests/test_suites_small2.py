"""Small-suite sweep, batch 2: elasticsearch, crate, ignite, chronos."""

import jepsen_tpu.db
import jepsen_tpu.os_
from fake_crate import FakeCrate
from fake_es_ignite import FakeElasticsearch, FakeIgnite
from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.independent import ktuple
from jepsen_tpu.suites import (chronos, crate, elasticsearch, ignite,
                               suite)


def test_suite_registry():
    assert suite("elasticsearch") is elasticsearch
    assert suite("crate") is crate
    assert suite("ignite") is ignite
    assert suite("chronos") is chronos


def _hermetic(t, tmp_path, **conn):
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t.update(conn)
    t["store-dir"] = str(tmp_path / "store")
    return core.run(t)


# -- elasticsearch -----------------------------------------------------------

def test_es_create_set_and_cas_set_clients():
    f = FakeElasticsearch()
    try:
        t = {"es-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = elasticsearch.CreateSetClient().open(t, "n1")
        for v in (1, 2, 3):
            assert c.invoke(t, {"type": "invoke", "f": "add",
                                "value": v,
                                "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                         "process": 0})
        assert r["type"] == "ok" and r["value"] == [1, 2, 3]

        c2 = elasticsearch.CASSetClient().open(t, "n1")
        c2.setup(t)
        assert c2.invoke(t, {"type": "invoke", "f": "add", "value": 9,
                             "process": 0})["type"] == "ok"
        r = c2.invoke(t, {"type": "invoke", "f": "read",
                          "value": None, "process": 0})
        assert r["value"] == [9]
    finally:
        f.stop()


def test_es_hermetic_runs(tmp_path):
    for workload in sorted(elasticsearch.WORKLOADS):
        f = FakeElasticsearch()
        try:
            t = elasticsearch.elasticsearch_test({
                "nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "workload": workload,
                "rate": 100, "time-limit": 3, "faults": ["none"]})
            done = _hermetic(
                t, tmp_path / workload,
                **{"es-url-fn":
                   lambda n: f"http://127.0.0.1:{f.port}"})
            assert done["results"]["valid?"] is True, \
                (workload, done["results"])
        finally:
            f.stop()


# -- crate -------------------------------------------------------------------

def test_crate_lost_updates_client():
    f = FakeCrate()
    try:
        t = {"crate-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = crate.LostUpdatesClient().open(t, "n1")
        for v in (0, 1, 2):
            assert c.invoke(t, {"type": "invoke", "f": "add",
                                "value": ktuple(1, v),
                                "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read",
                         "value": ktuple(1, None), "process": 0})
        assert r["type"] == "ok" and r["value"][1] == [0, 1, 2]
    finally:
        f.stop()


def test_crate_version_divergence_checker():
    h = [
        {"type": "ok", "f": "read", "value": [3, 7], "process": 0,
         "time": 0},
        {"type": "ok", "f": "read", "value": [3, 7], "process": 1,
         "time": 1},
    ]
    r = crate.MultiVersionChecker().check({}, h, {})
    assert r["valid?"] is True
    h.append({"type": "ok", "f": "read", "value": [4, 7],
              "process": 2, "time": 2})
    r = crate.MultiVersionChecker().check({}, h, {})
    assert r["valid?"] is False and r["divergent"] == {7: [3, 4]}


def test_crate_dirty_read_checker():
    h = [
        {"type": "ok", "f": "write", "value": 1, "process": 0},
        {"type": "ok", "f": "write", "value": 2, "process": 0},
        {"type": "ok", "f": "read", "value": 1, "process": 1},
        {"type": "ok", "f": "strong-read", "value": [1, 2],
         "process": 2},
        {"type": "ok", "f": "strong-read", "value": [1, 2],
         "process": 3},
    ]
    r = crate.DirtyReadChecker().check({}, h, {})
    assert r["valid?"] is True
    # a read of a row no strong read ever saw is dirty
    h.append({"type": "ok", "f": "read", "value": 99, "process": 1})
    r = crate.DirtyReadChecker().check({}, h, {})
    assert r["valid?"] is False and r["dirty"] == [99]


def test_crate_hermetic_runs(tmp_path):
    for workload in ("lost-updates", "version-divergence"):
        f = FakeCrate()
        try:
            t = crate.crate_test({
                "nodes": ["n1", "n2", "n3"], "concurrency": 3,
                "ssh": {"dummy": True}, "workload": workload,
                "rate": 100, "time-limit": 3, "faults": ["none"]})
            done = _hermetic(
                t, tmp_path / workload,
                **{"crate-url-fn":
                   lambda n: f"http://127.0.0.1:{f.port}"})
            assert done["results"]["valid?"] is True, \
                (workload, done["results"])
        finally:
            f.stop()


def test_crate_dirty_read_hermetic(tmp_path):
    f = FakeCrate()
    try:
        t = crate.crate_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "workload": "dirty-read",
            "rate": 200, "time-limit": 3, "faults": ["none"],
            "writers": 2})
        done = _hermetic(
            t, tmp_path,
            **{"crate-url-fn": lambda n: f"http://127.0.0.1:{f.port}"})
        w = done["results"]["workload"]
        # reads may race ahead of the single fake's visibility, but
        # nothing may be lost and strong reads must agree
        assert w["nodes-agree?"] is True
        assert not w["lost"], w
    finally:
        f.stop()


# -- ignite ------------------------------------------------------------------

def test_ignite_register_client():
    f = FakeIgnite()
    try:
        t = {"ignite-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = ignite.RegisterClient().open(t, "n1")
        assert c.invoke(t, {"type": "invoke", "f": "write",
                            "value": ktuple(0, 3),
                            "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(0, (3, 4)), "process": 0})
        assert r["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(0, (9, 5)), "process": 0})
        assert r["type"] == "fail"
        r = c.invoke(t, {"type": "invoke", "f": "read",
                         "value": ktuple(0, None), "process": 0})
        assert r["value"][1] == 4
    finally:
        f.stop()


def test_ignite_hermetic_runs(tmp_path):
    for workload in sorted(ignite.WORKLOADS):
        f = FakeIgnite()
        try:
            t = ignite.ignite_test({
                "nodes": ["n1", "n2", "n3"], "concurrency": 6,
                "ssh": {"dummy": True}, "workload": workload,
                "rate": 200, "accounts": [0, 1, 2, 3],
                "time-limit": 3, "faults": ["none"]})
            done = _hermetic(
                t, tmp_path / workload,
                **{"ignite-url-fn":
                   lambda n: f"http://127.0.0.1:{f.port}"})
            assert done["results"]["valid?"] is True, \
                (workload, done["results"])
        finally:
            f.stop()


# -- chronos -----------------------------------------------------------------

def test_chronos_job_targets_and_solution():
    job = {"name": 1, "start_epoch": 100.0, "count": 3,
           "interval": 50, "epsilon": 10, "duration": 5}
    targets = chronos.job_targets(300.0, job)
    assert targets == [(100.0, 115.0), (150.0, 165.0), (200.0, 215.0)]
    runs = [{"name": 1, "start": 101.0, "end": 106.0},
            {"name": 1, "start": 152.0, "end": 157.0},
            {"name": 1, "start": 203.0, "end": 208.0}]
    s = chronos.job_solution(300.0, job, runs)
    assert s["valid?"] is True and s["extra"] == []
    # a missing run invalidates
    s2 = chronos.job_solution(300.0, job, runs[:2])
    assert s2["valid?"] is False
    # an incomplete run doesn't count
    runs[2] = {"name": 1, "start": 203.0, "end": None}
    s3 = chronos.job_solution(300.0, job, runs)
    assert s3["valid?"] is False and s3["incomplete"] == 1


def test_chronos_checker_end_to_end():
    job = {"name": 1, "start_epoch": 10.0, "count": 2,
           "interval": 100, "epsilon": 10, "duration": 2}
    hist = [
        {"type": "ok", "f": "add-job", "value": job, "process": 0,
         "time": 0},
        {"type": "ok", "f": "read", "process": 0, "time": 1,
         "read-time": 400.0,
         "value": [
             {"name": 1, "start": 12.0, "end": 14.0, "node": "n1"},
             {"name": 1, "start": 111.0, "end": 113.0, "node": "n2"},
             {"name": 1, "start": 250.0, "end": 252.0, "node": "n1"},
         ]},
    ]
    r = chronos.JobRunChecker().check({}, hist, {})
    assert r["valid?"] is True, r
    # drop the second run: target unsatisfied
    hist[1]["value"] = [hist[1]["value"][0], hist[1]["value"][2]]
    r = chronos.JobRunChecker().check({}, hist, {})
    assert r["valid?"] is False


def test_chronos_db_commands():
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"]}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            chronos.db().setup(test, "n1")
            chronos.db().teardown(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "service zookeeper restart" in cmds
    assert "service mesos-master restart" in cmds
    assert "service chronos restart" in cmds
    stdins = " ".join(a.get("in", "") for _h, _c, a in log
                      if isinstance(a.get("in"), str))
    assert "zk://n1:2181,n2:2181,n3:2181/mesos" in stdins


def test_chronos_hermetic_run(tmp_path):
    """Full core.run against the fake scheduler: jobs submitted over
    real HTTP, run logs read back through the dummy remote, and the
    job-run checker issuing a substantive verdict."""
    from fake_chronos import FakeChronos

    f = FakeChronos()
    try:
        t = chronos.chronos_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"],
            "job-interval": 0.4, "job-start-delay": -120})
        t["remote"] = dummy.remote(responses={
            r"\bls\b|\bcat\b": f.remote_responder})
        done = _hermetic(
            t, tmp_path,
            **{"chronos-url-fn":
               lambda n: f"http://127.0.0.1:{f.port}"})
        assert done["results"]["valid?"] is True, done["results"]
        w = done["results"]["workload"]
        assert w["job-count"] >= 3, "jobs must be submitted"
        assert any(s["complete"] > 0 for s in w["jobs"].values()), \
            "past-scheduled jobs must show completed runs"
    finally:
        f.stop()


def test_chronos_hermetic_run_catches_dropped_runs(tmp_path):
    """A scheduler that silently skips due runs must be flagged."""
    from fake_chronos import FakeChronos

    f = FakeChronos(drop=2)
    try:
        t = chronos.chronos_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"],
            "job-interval": 0.4, "job-start-delay": -120})
        t["remote"] = dummy.remote(responses={
            r"\bls\b|\bcat\b": f.remote_responder})
        done = _hermetic(
            t, tmp_path,
            **{"chronos-url-fn":
               lambda n: f"http://127.0.0.1:{f.port}"})
        assert done["results"]["workload"]["valid?"] is False
    finally:
        f.stop()


def test_chronos_error_classification(tmp_path):
    """A dead scheduler endpoint classifies as a definite fail."""
    c = chronos.Client().open({"chronos-url-fn":
                               lambda n: "http://127.0.0.1:1"}, "n1")
    r = c.invoke({}, {"type": "invoke", "f": "add-job", "process": 0,
                      "value": {"name": 1, "start": "2026-01-01T00:00:00Z",
                                "count": 1, "duration": 1, "epsilon": 10,
                                "interval": 30}})
    assert r["type"] == "fail" and r["error"]
