"""The observability substrate (jepsen_tpu/telemetry.py + the trace
exporter rework): registry semantics, Prometheus exposition, the
/metrics + /healthz HTTP endpoints against a live verification
service, chunk-level span threading (one trace id run -> stream ->
chunk), the async trace flusher, and the profiler hooks' no-op
contract."""

from __future__ import annotations

import json
import re
import socket as _socket
import threading
import time
import urllib.request

import pytest

from jepsen_tpu import telemetry, trace

CHUNK = 64
SLOTS = 8
FRONTIER = 128


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Zero every metric's accumulated values between tests (metric
    declarations are module-level and survive)."""
    telemetry.reset()
    telemetry.set_enabled(True)
    yield
    telemetry.reset()
    telemetry.set_enabled(True)


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    from jepsen_tpu import _platform
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


# -- registry semantics ------------------------------------------------------

def test_counter_labels_and_idempotent_registration():
    c = telemetry.counter("jepsen_tpu_run_lint_test_total", "t",
                          ("kind",))
    c2 = telemetry.counter("jepsen_tpu_run_lint_test_total", "t",
                           ("kind",))
    assert c is c2          # get-or-create, one family per name
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc(5)
    snap = telemetry.snapshot()["jepsen_tpu_run_lint_test_total"]
    assert snap == {"kind=a": 3.0, "kind=b": 5.0}
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    with pytest.raises(ValueError):
        c.labels(kind="a").inc(-1)
    with pytest.raises(ValueError):   # type change is a bug
        telemetry.gauge("jepsen_tpu_run_lint_test_total", "t",
                        ("kind",))
    with pytest.raises(ValueError):   # label change is a bug
        telemetry.counter("jepsen_tpu_run_lint_test_total", "t",
                          ("other",))


def test_gauge_and_unlabeled_passthrough():
    g = telemetry.gauge("jepsen_tpu_run_lint_gauge_info", "t")
    g.set(4.5)
    g.inc()
    g.dec(2)
    assert telemetry.snapshot()[
        "jepsen_tpu_run_lint_gauge_info"][""] == 3.5


def test_histogram_buckets_sum_count():
    h = telemetry.histogram("jepsen_tpu_run_lint_hist_seconds", "t",
                            buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = telemetry.snapshot()["jepsen_tpu_run_lint_hist_seconds"][""]
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(56.05)
    assert snap["buckets"] == {"0.1": 1, "1.0": 2, "10.0": 1,
                               "+Inf": 1}


def test_histogram_time_context_manager():
    h = telemetry.histogram("jepsen_tpu_run_lint_timer_seconds", "t")
    with h.time():
        time.sleep(0.01)
    snap = telemetry.snapshot()["jepsen_tpu_run_lint_timer_seconds"][""]
    assert snap["count"] == 1
    assert snap["sum"] >= 0.01


def test_concurrent_increments_are_exact():
    c = telemetry.counter("jepsen_tpu_run_lint_race_total", "t")
    h = telemetry.histogram("jepsen_tpu_run_lint_race_seconds", "t")
    n, threads = 5000, 8

    def work():
        for _ in range(n):
            c.inc()
            h.observe(0.001)

    ts = [threading.Thread(target=work) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = telemetry.snapshot()
    assert snap["jepsen_tpu_run_lint_race_total"][""] == n * threads
    assert snap["jepsen_tpu_run_lint_race_seconds"][""]["count"] \
        == n * threads


def test_set_enabled_turns_mutations_into_noops():
    c = telemetry.counter("jepsen_tpu_run_lint_off_total", "t")
    prev = telemetry.set_enabled(False)
    try:
        c.inc(100)
    finally:
        telemetry.set_enabled(prev)
    c.inc(1)
    assert telemetry.snapshot()["jepsen_tpu_run_lint_off_total"][""] \
        == 1


# -- Prometheus exposition ---------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def _assert_prometheus_parseable(text: str) -> dict:
    """Every non-comment line must be `name{labels} value`; returns
    {name: [line, ...]} for content assertions."""
    by_name: dict = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        assert _PROM_LINE.match(line), f"unparseable: {line!r}"
        by_name.setdefault(line.split("{")[0].split(" ")[0],
                           []).append(line)
    return by_name


def test_prometheus_text_format():
    c = telemetry.counter("jepsen_tpu_run_lint_fmt_total", "t",
                          ("kind",))
    c.labels(kind='we"ird\nvalue').inc()
    h = telemetry.histogram("jepsen_tpu_run_lint_fmt_seconds", "t",
                            buckets=(1.0, 5.0))
    h.observe(0.5)
    h.observe(2.0)
    h.observe(99.0)
    text = telemetry.prometheus_text()
    lines = _assert_prometheus_parseable(text)
    assert "# TYPE jepsen_tpu_run_lint_fmt_total counter" \
        in text.splitlines()
    assert "# TYPE jepsen_tpu_run_lint_fmt_seconds histogram" \
        in text.splitlines()
    # label escaping round-trips quotes/newlines
    [counter_line] = lines["jepsen_tpu_run_lint_fmt_total"]
    assert '\\"' in counter_line and "\\n" in counter_line
    # histogram buckets are cumulative and +Inf equals the count
    bkt = lines["jepsen_tpu_run_lint_fmt_seconds_bucket"]
    assert [ln.rsplit(" ", 1)[1] for ln in bkt] == ["1", "2", "3"]
    assert lines["jepsen_tpu_run_lint_fmt_seconds_count"][0] \
        .endswith(" 3")
    # HELP/TYPE appear for registered metrics even with no series yet
    telemetry.counter("jepsen_tpu_run_lint_empty_total", "t",
                      ("kind",))
    assert "# TYPE jepsen_tpu_run_lint_empty_total counter" \
        in telemetry.prometheus_text()


def test_metric_name_lint_is_clean():
    import sys
    from pathlib import Path
    root = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(root))
    try:
        # the metrics analyzer migrated into tools/staticcheck; the
        # live registry must lint clean against the naming convention
        from tools.staticcheck.metrics import lint_registry
        problems, n = lint_registry(str(root))
        assert problems == [] and n > 0
    finally:
        sys.path.remove(str(root))


# -- the instrumented pipeline ----------------------------------------------

def _hist(seed, n=300, corrupt=False):
    from jepsen_tpu.checker import synth
    h = synth.register_history(n, concurrency=3, values=5, seed=seed)
    if corrupt:
        h = synth.corrupt(h, seed=seed + 1)
    return h


def test_offline_analysis_populates_wgl_metrics():
    from jepsen_tpu import models
    from jepsen_tpu.checker.wgl import analysis_tpu

    a = analysis_tpu(models.cas_register(), _hist(7, n=400),
                     chunk_entries=64)
    assert a["valid?"] is True
    snap = telemetry.snapshot()
    assert sum(snap["jepsen_tpu_wgl_checked_ops_total"].values()) > 0
    assert sum(snap["jepsen_tpu_wgl_engine_decisions_total"]
               .values()) >= 1
    chunk = snap["jepsen_tpu_wgl_chunk_seconds"]
    assert any(v["count"] > 0 for v in chunk.values())
    # attestation is default-on: staged-buffer digests verified
    assert sum(snap["jepsen_tpu_abft_verifications_total"]
               .values()) > 0


def test_recovery_rung_counter_counts_injected_faults(monkeypatch):
    from jepsen_tpu import models
    from jepsen_tpu.checker.wgl import analysis_tpu

    monkeypatch.setenv("JEPSEN_TPU_FAULT_INJECT", "oom@offline:1")
    a = analysis_tpu(models.cas_register(), _hist(11, n=200))
    assert a["valid?"] is True
    assert a["recovered"]["faults"] == ["oom"]
    snap = telemetry.snapshot()["jepsen_tpu_wgl_recovery_rungs_total"]
    assert snap.get("kind=oom,site=offline") == 1


def test_screen_metrics_and_escalation_reasons():
    from jepsen_tpu import models
    from jepsen_tpu.checker import screen

    sc = screen.screen_history(models.cas_register(), _hist(13))
    assert sc["valid?"] is True
    esc, why = screen.should_escalate({"screenable": False})
    assert esc and why == "unscreened-model"
    esc, why = screen.should_escalate({"suspicion": 2.0})
    assert esc and why == "suspicion"
    snap = telemetry.snapshot()
    assert sum(snap["jepsen_tpu_screen_screened_ops_total"]
               .values()) >= sc["op-count"]
    e = snap["jepsen_tpu_screen_escalations_total"]
    assert e.get("why=unscreened-model") == 1
    assert e.get("why=suspicion") == 1


# -- span threading: run -> stream -> chunk -> recovery-retry ---------------

def test_stream_spans_thread_one_trace_id(tmp_path, monkeypatch):
    from jepsen_tpu import models
    from jepsen_tpu.checker import streaming

    trace.tracing(str(tmp_path / "spans.jsonl"))
    try:
        monkeypatch.setenv("JEPSEN_TPU_FAULT_INJECT",
                           "oom@stream-chunk:2")
        h = _hist(17, n=400, corrupt=True)
        r = streaming.stream_check(
            models.cas_register(), h, chunk_entries=CHUNK,
            slots=SLOTS, frontier=FRONTIER, checkpoint_every=2)
        assert r["valid?"] is False
        assert r["recovered"]["faults"] == ["oom"]
        tid = r["trace-id"]
        assert tid
        tr = trace.tracer()
        chunks = tr.spans("wgl.stream.chunk")
        assert chunks and all(s["traceID"] == tid for s in chunks)
        retries = tr.spans("wgl.stream.recovery-retry")
        assert retries and all(s["traceID"] == tid for s in retries)
        [stream_span] = tr.spans("wgl.stream")
        assert stream_span["traceID"] == tid
        # chunks parent to the stream span — the run->stream->chunk
        # thread a Jaeger UI renders as one tree
        assert all(s["parentSpanID"] == stream_span["spanID"]
                   for s in chunks)
        # the violation tagged the stream span
        tags = {t["key"]: t["value"] for t in stream_span["tags"]}
        assert tags.get("violation") == "true"
    finally:
        trace.tracing(None)


def test_untraced_stream_has_no_trace_id():
    from jepsen_tpu import models
    from jepsen_tpu.checker import streaming

    r = streaming.stream_check(models.cas_register(), _hist(19),
                               chunk_entries=CHUNK, slots=SLOTS)
    assert r["valid?"] is True
    assert "trace-id" not in r


# -- the async trace flusher -------------------------------------------------

def _slow_collector():
    """A TCP listener that accepts but never answers — the shape of a
    wedged Jaeger collector (connects succeed, responses never come)."""
    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    stop = threading.Event()
    conns = []

    def loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                conns.append(c)
            except OSError:
                continue

    t = threading.Thread(target=loop, daemon=True)
    t.start()

    def teardown():
        stop.set()
        t.join(2)
        for c in conns:
            c.close()
        srv.close()

    return srv.getsockname()[1], teardown


def test_slow_collector_does_not_stall_span_creation():
    port, teardown = _slow_collector()
    tr = trace.Tracer(f"http://127.0.0.1:{port}/api/traces")
    try:
        t0 = time.monotonic()
        for i in range(100):
            with tr.span(f"hot-{i}"):
                pass
        create_s = time.monotonic() - t0
        # the old exporter paid a synchronous POST (1 s timeout) per
        # span: 100 spans against this collector took >100 s; the
        # batched flusher makes creation pure enqueue
        assert create_s < 1.0, \
            f"span creation stalled {create_s:.2f}s on a slow collector"
        assert len(tr.spans()) == 100
        t0 = time.monotonic()
        tr.close()
        assert time.monotonic() - t0 < 5.0, "close() unbounded"
    finally:
        teardown()


def test_unreachable_collector_and_queue_bound():
    # nothing listens here: connects fail fast, spans still record
    tr = trace.Tracer("http://127.0.0.1:9/api/traces")
    try:
        for i in range(trace.EXPORT_QUEUE_LIMIT + 50):
            with tr.span("x"):
                pass
        with tr.lock:
            assert len(tr._q) <= trace.EXPORT_QUEUE_LIMIT
    finally:
        tr.close()


def test_file_exporter_still_synchronous(tmp_path):
    p = tmp_path / "t.jsonl"
    tr = trace.Tracer(str(p))
    with tr.span("a"):
        trace_id = tr.context()
    tr.close()
    [doc] = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert doc["operationName"] == "a"
    del trace_id


# -- profiler hooks ----------------------------------------------------------

def test_profile_section_is_noop_without_env(monkeypatch):
    monkeypatch.delenv(telemetry.PROFILE_ENV, raising=False)
    assert telemetry.profile_dir() is None
    with telemetry.profile_section("wgl.test.chunk"):
        pass
    assert telemetry._profiler_started is False


def test_profile_section_starts_trace_with_env(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.PROFILE_ENV, str(tmp_path))
    try:
        with telemetry.profile_section("wgl.test.chunk"):
            pass
        started = telemetry._profiler_started
    finally:
        telemetry.stop_profiler()
    assert telemetry._profiler_started is False
    # best-effort: when jax's profiler is available the trace started
    # and stop_trace wrote the artifact dir; otherwise the no-op path
    # ran (still a pass — profiling must never be load-bearing)
    if started:
        assert any(tmp_path.iterdir())


# -- /metrics + /healthz e2e against a live service -------------------------

def _get(url: str) -> tuple[int, str]:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_metrics_http_e2e_two_fake_etcd_streams(tmp_path, monkeypatch):
    """The acceptance drive: a service with --metrics-port serving two
    concurrent fake-etcd runs; /metrics returns Prometheus-parseable
    text carrying chunk-latency histograms and recovery/escalation/
    attest counters, /healthz the status() JSON (uptime_s + telemetry
    sub-map)."""
    import random

    from fake_etcd import FakeEtcd

    import jepsen_tpu.db
    import jepsen_tpu.os_
    from jepsen_tpu import core, generator as gen, models, service
    from jepsen_tpu.checker import linearizable
    from jepsen_tpu.suites import etcd

    # one deterministic recovery fault on run 0's stream, so the
    # recovery-rung counter has a live series to expose
    monkeypatch.setenv(
        "JEPSEN_TPU_FAULT_INJECT",
        "oom@stream-chunk/etcd-metrics-0/now0:1")

    svc = service.VerificationService()
    addr = svc.serve("127.0.0.1:0")
    msrv = telemetry.serve_metrics(0, host="127.0.0.1",
                                   healthz=svc.status)
    mport = msrv.server_address[1]

    fakes = [FakeEtcd(), FakeEtcd()]
    for f in fakes:
        f.port = f.start()

    def make_test(i, fake):
        rng = random.Random(4200 + i)
        return {
            "name": f"etcd-metrics-{i}",
            "start-time": f"now{i}",
            "nodes": ["n1", "n2", "n3"],
            "ssh": {"dummy": True},
            "db": jepsen_tpu.db.noop,
            "os": jepsen_tpu.os_.noop,
            "client": etcd.EtcdClient(),
            "client-url-fn":
                lambda node: f"http://127.0.0.1:{fake.port}",
            "concurrency": 4,
            "store-dir": str(tmp_path / "store"),
            "checker": linearizable(models.cas_register()),
            "service": addr,
            "online-chunk-entries": CHUNK,
            "online-checkpoint-every": 2,
            "generator": gen.clients(gen.limit(150, gen.mix([
                lambda: {"f": "read"},
                lambda: {"f": "write",
                         "value": rng.randint(0, 4)},
                lambda: {"f": "cas",
                         "value": [rng.randint(0, 4),
                                   rng.randint(0, 4)]},
            ]))),
        }

    done: dict = {}

    def run_one(i, fake):
        done[i] = core.run(make_test(i, fake))

    ths = [threading.Thread(target=run_one, args=(i, f))
           for i, f in enumerate(fakes)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(300)
    for f in fakes:
        f.stop()
    try:
        assert sorted(done) == [0, 1]
        for i in (0, 1):
            assert done[i]["results"]["valid?"] is True, \
                done[i]["results"]

        code, text = _get(f"http://127.0.0.1:{mport}/metrics")
        assert code == 200
        lines = _assert_prometheus_parseable(text)
        # chunk-latency histograms from the served streams
        assert any(
            'site="stream"' in ln and ln.rsplit(" ", 1)[1] != "0"
            for ln in lines.get("jepsen_tpu_wgl_chunk_seconds_count",
                                []))
        # recovery climbed a rung on the faulted stream
        assert any("kind=\"oom\"" in ln for ln in lines.get(
            "jepsen_tpu_wgl_recovery_rungs_total", []))
        # attestation verified staged buffers; escalation counter is
        # cataloged (HELP/TYPE) even when this run never escalated
        assert any(ln.rsplit(" ", 1)[1] != "0" for ln in lines.get(
            "jepsen_tpu_abft_verifications_total", []))
        assert "jepsen_tpu_screen_escalations_total" in text
        # two admitted streams reached verdicts
        assert any(
            'event="admitted"' in ln and ln.endswith(" 2")
            for ln in lines.get(
                "jepsen_tpu_service_stream_events_total", []))

        code, body = _get(f"http://127.0.0.1:{mport}/healthz")
        assert code == 200
        st = json.loads(body)
        assert st["uptime_s"] > 0
        assert "telemetry" in st
        assert len(st["streams"]) == 2

        # the socket 'metrics' verb answers the same registry
        host, _, port = addr.rpartition(":")
        conn = _socket.create_connection((host, int(port)))
        rf = conn.makefile("r")
        conn.sendall((json.dumps({"type": "metrics", "id": 1})
                      + "\n").encode())
        m = json.loads(rf.readline())
        assert m["ok"] is True
        assert "jepsen_tpu_service_stream_events_total" in m["metrics"]
        conn.close()
    finally:
        msrv.shutdown()
        svc.stop()


def test_service_status_carries_uptime_and_telemetry():
    from jepsen_tpu import service

    svc = service.VerificationService()
    st = svc.status()
    assert st["uptime_s"] >= 0
    assert isinstance(st["telemetry"], dict)


# -- surfacing ---------------------------------------------------------------

def test_report_telemetry_line():
    from jepsen_tpu import report

    line = report.telemetry_line({
        "linear": {"chunks": 12,
                   "recovered": {"faults": ["oom", "corrupt"],
                                 "retries": 2}},
        "elle": {"escalated": {"why": "suspicion"}},
    })
    assert "12 device chunks" in line
    assert "1 escalated" in line
    assert "2 recovery retries" in line
    assert "1 attest failures" in line
    # older stored results carry none of it
    assert report.telemetry_line({"valid?": True}) == ""
    assert report.telemetry_line({}) == ""
    assert report.telemetry_line(None) == ""


def test_web_metrics_route(tmp_path):
    from jepsen_tpu import web

    server = web.serve({"host": "127.0.0.1", "port": 0,
                        "store-dir": str(tmp_path)})
    port = server.server_address[1]
    try:
        code, home = _get(f"http://127.0.0.1:{port}/")
        assert code == 200 and "/metrics" in home
        code, text = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        _assert_prometheus_parseable(text)
        assert "jepsen_tpu_web_requests_total" in text
    finally:
        server.shutdown()


def test_cli_service_has_metrics_port_option():
    from jepsen_tpu import cli

    longs = [o["long"]
             for o in cli.service_cmd()["service"]["opt_spec"]]
    assert "--metrics-port" in longs
