"""In-process fake CrateDB: the HTTP `_sql` endpoint over a tiny
store with per-row MVCC `_version` columns — the subset
`jepsen_tpu/suites/crate.py` issues."""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeCrate:
    def __init__(self):
        self.lock = threading.Lock()
        # table -> {id: {"cols": {...}, "_version": n}}
        self.tables: dict[str, dict] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                try:
                    with outer.lock:
                        out = outer.sql(req.get("stmt", ""),
                                        req.get("args") or [])
                    body = json.dumps(out).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps(
                        {"error": {"code": 4000,
                                   "message": str(e)}}).encode()
                    self.send_response(400)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()

    def sql(self, stmt: str, args: list) -> dict:
        s = stmt.strip().rstrip(";")
        low = s.lower()
        if low.startswith("create table"):
            name = re.match(
                r"create table (?:if not exists )?(\w+)", low).group(1)
            self.tables.setdefault(name, {})
            return {"rowcount": 1, "rows": []}
        if low.startswith("refresh table"):
            return {"rowcount": 1, "rows": []}
        m = re.match(r"insert into (\w+) \(([^)]*)\)\s*values", low)
        if m:
            tbl = self.tables.setdefault(m.group(1), {})
            cols = [c.strip() for c in m.group(2).split(",")]
            row = dict(zip(cols, args))
            key = row.get("id")
            if key in tbl:
                raise ValueError("DuplicateKeyException")
            tbl[key] = {"cols": row, "_version": 1}
            return {"rowcount": 1, "rows": []}
        m = re.match(
            r"select (.*?) from (\w+)(?:\s+where id = (\?|\d+))?$",
            low)
        if m:
            cols = [c.strip() for c in m.group(1).split(",")]
            tbl = self.tables.setdefault(m.group(2), {})
            if m.group(3) == "?":
                key = args[0]
                rows = [tbl[key]] if key in tbl else []
            elif m.group(3):
                key = int(m.group(3))
                rows = [tbl[key]] if key in tbl else []
            else:
                rows = list(tbl.values())
            out = [[r["_version"] if c.strip('\'"') == "_version"
                    else r["cols"].get(c.strip('\'"')) for c in cols]
                   for r in rows]
            return {"rowcount": len(out), "rows": out}
        m = re.match(
            r"update (\w+) set (\w+) = \? where id = \?"
            r"(?: and _version = \?)?$", low)
        if m:
            tbl = self.tables.setdefault(m.group(1), {})
            col = m.group(2)
            val, key = args[0], args[1]
            row = tbl.get(key)
            if row is None:
                return {"rowcount": 0, "rows": []}
            if "_version" in low and row["_version"] != args[2]:
                return {"rowcount": 0, "rows": []}
            row["cols"][col] = val
            row["_version"] += 1
            return {"rowcount": 1, "rows": []}
        raise ValueError(f"unsupported statement: {stmt!r}")
