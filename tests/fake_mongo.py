"""An in-process fake mongod speaking OP_MSG/BSON, implementing the
commands the mongodb suite's client issues (find, update with upsert,
findAndModify, insert, replSetInitiate), backed by in-memory
collections with a global lock."""

from __future__ import annotations

import socketserver

from netutil import NodelayHandler
import struct
import threading

import sys
import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from jepsen_tpu.suites.bson_proto import decode_doc, encode_doc  # noqa: E402

OP_MSG = 2013


class _Handler(NodelayHandler):
    def setup(self):
        super().setup()
        # registered so stop() can kill live sessions (tests rely on
        # in-flight clients observing server death); the stopped flag
        # is checked under the same lock stop() drains with, so a
        # connection accepted during shutdown can't escape the close
        srv: "FakeMongo" = self.server  # type: ignore[assignment]
        self._rejected = False
        with srv.lock:
            if srv._stopped:
                self.request.close()
                self._rejected = True
                return
            srv._conns.append(self.request)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def handle(self):
        if self._rejected:
            # connection was closed in setup() during shutdown; a recv
            # here would raise and spray handle_error tracebacks
            return
        srv: "FakeMongo" = self.server  # type: ignore[assignment]
        try:
            while True:
                header = self._read_exact(16)
                length, rid, _rto, opcode = struct.unpack("<iiii",
                                                          header)
                payload = self._read_exact(length - 16)
                if opcode != OP_MSG:
                    return
                cmd = decode_doc(payload[5:])
                if srv.fail_hook:
                    err = srv.fail_hook(cmd)
                    if err:
                        reply = {"ok": 0, "code": err[0],
                                 "errmsg": err[1]}
                    else:
                        reply = srv.dispatch(cmd)
                else:
                    reply = srv.dispatch(cmd)
                body = struct.pack("<I", 0) + b"\x00" + encode_doc(reply)
                self.request.sendall(
                    struct.pack("<iiii", 16 + len(body), 1, rid,
                                OP_MSG) + body)
        except (ConnectionError, OSError):
            pass


class FakeMongo(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        self.colls: dict = {}
        self.lock = threading.Lock()
        self._conns: list = []
        self._stopped = False
        self.fail_hook = None  # fail_hook(cmd) -> (code, msg) | None
        self.initiated = False
        super().__init__(("127.0.0.1", 0), _Handler)
        self.port = self.server_address[1]
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        """Close the listener AND every accepted session socket, so
        in-flight clients deterministically see the server die."""
        self.shutdown()
        self.server_close()
        with self.lock:
            self._stopped = True
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _coll(self, cmd, name) -> list:
        return self.colls.setdefault((cmd["$db"], name), [])

    @staticmethod
    def _matches(doc, query) -> bool:
        for k, v in query.items():
            cur = doc.get(k)
            if isinstance(v, dict) and "$ne" in v:
                t = v["$ne"]
                if (t in cur) if isinstance(cur, list) else (cur == t):
                    return False
                continue
            if isinstance(v, dict) and "$size" in v:
                if len(cur or []) != v["$size"]:
                    return False
                continue
            if isinstance(cur, list) and not isinstance(v, list):
                if v not in cur:
                    return False
                continue
            if cur != v:
                return False
        return True

    @staticmethod
    def _apply_update(doc, u) -> None:
        doc.update(u.get("$set", {}))
        for k, d in u.get("$inc", {}).items():
            doc[k] = doc.get(k, 0) + d
        for k, v in u.get("$push", {}).items():
            doc.setdefault(k, []).append(v)
        for k, v in u.get("$pull", {}).items():
            if v in doc.get(k, []):
                doc[k] = [x for x in doc[k] if x != v]
        # $currentDate ignored (no clock semantics in the fake)

    def dispatch(self, cmd: dict) -> dict:
        with self.lock:
            if "replSetInitiate" in cmd:
                if self.initiated:
                    return {"ok": 0, "code": 23,
                            "errmsg": "already initialized"}
                self.initiated = True
                return {"ok": 1}
            if "hello" in cmd or "ping" in cmd or "isMaster" in cmd:
                return {"ok": 1, "isWritablePrimary": True}
            if "find" in cmd:
                coll = self._coll(cmd, cmd["find"])
                docs = [d for d in coll
                        if self._matches(d, cmd.get("filter") or {})]
                if cmd.get("limit"):
                    docs = docs[:cmd["limit"]]
                return {"ok": 1, "cursor": {"id": 0, "firstBatch": docs,
                                            "ns": "jepsen"}}
            if "insert" in cmd:
                coll = self._coll(cmd, cmd["insert"])
                coll.extend(cmd["documents"])
                return {"ok": 1, "n": len(cmd["documents"])}
            if "findAndModify" in cmd:  # before 'update': fAM carries
                # an 'update' field of its own
                coll = self._coll(cmd, cmd["findAndModify"])
                hit = [d for d in coll
                       if self._matches(d, cmd.get("query") or {})]
                if hit:
                    self._apply_update(hit[0], cmd["update"])
                    return {"ok": 1, "value": hit[0],
                            "lastErrorObject":
                                {"updatedExisting": True, "n": 1}}
                return {"ok": 1, "value": None,
                        "lastErrorObject":
                            {"updatedExisting": False, "n": 0}}
            if "update" in cmd:
                coll = self._coll(cmd, cmd["update"])
                n = 0
                for u in cmd["updates"]:
                    hit = [d for d in coll if self._matches(d, u["q"])]
                    if hit:
                        for d in hit:
                            self._apply_update(d, u["u"])
                            n += 1
                    elif u.get("upsert"):
                        doc = {k: v for k, v in u["q"].items()
                               if not isinstance(v, dict)}
                        self._apply_update(doc, u["u"])
                        coll.append(doc)
                        n += 1
                return {"ok": 1, "n": n}
        return {"ok": 0, "code": 59,
                "errmsg": f"no such command: {next(iter(cmd))}"}
