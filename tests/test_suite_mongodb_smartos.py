"""SmartOS OS + mongodb-smartos suite tests: pkgin/svcadm command
generation against the recording dummy remote, transfer-protocol
client semantics against the extended fake mongod, and hermetic
end-to-end runs."""

import pytest

import jepsen_tpu.db
import jepsen_tpu.os_
from fake_mongo import FakeMongo
from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.os_ import smartos
from jepsen_tpu.suites import mongodb_smartos, suite
from jepsen_tpu.suites.bson_proto import Conn


def test_suite_registry():
    assert suite("mongodb-smartos") is mongodb_smartos


# -- smartos OS --------------------------------------------------------------

def test_smartos_setup_commands():
    log = []
    remote = dummy.remote(log=log, responses={
        r"hostname$": "n1",
        r"cat /etc/hosts": "127.0.0.1\tlocalhost\n::1 localhost",
        r"date \+%s": "1000000",
        r"stat -c %Y": "0",          # ancient pkgin db: update fires
        r"pkgin -p list": "wget-1.21;downloader\ncurl-8.0;client",
    })
    test = {"nodes": ["n1"], "net": __import__("jepsen_tpu.net",
                                              fromlist=["noop"]).noop}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            smartos.os.setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "pkgin update" in cmds
    assert "pkgin -y install" in cmds
    # already-installed packages are not reinstalled
    assert "install wget" not in cmds.replace("vim unzip", "")
    assert "svcadm enable -r ipfilter" in cmds
    # hostfile got the hostname appended to the loopback line
    stdins = " ".join(a.get("in", "") for _h, _c, a in log
                      if isinstance(a.get("in"), str))
    assert "127.0.0.1\tlocalhost n1" in stdins


def test_smartos_pkgin_version_parsing():
    remote = dummy.remote(responses={
        r"pkgin -p list":
            "mongodb-3.4.4;database\nmongo-tools-3.4.4;tools\n"
            "pcre2-10.42;regex",
    })
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            assert smartos.installed_version("mongodb") == "3.4.4"
            assert smartos.installed_version("pcre2") == "10.42"
            assert smartos.installed_version("nope") is None
            assert smartos.installed(["mongodb", "nope"]) == {"mongodb"}
            assert smartos.installed_p("mongo-tools")
            assert not smartos.installed_p(["mongodb", "nope"])


def test_db_setup_commands():
    log = []
    remote = dummy.remote(log=log, responses={r"pkgin -p list": ""})
    f = FakeMongo()
    try:
        test = {"nodes": ["n1", "n2", "n3"],
                "mongo-conn-fn": lambda n: Conn("127.0.0.1", f.port)}
        db = mongodb_smartos.db()
        with control.with_remote(remote):
            sess = control.session("n1")
            with control.with_session("n1", sess):
                db.setup(test, "n1")
                db.teardown(test, "n1")
        cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
        assert "pkgin -y install mongodb-3.4.4" in cmds
        assert "pkgin -y install mongo-tools-3.4.4" in cmds
        assert "svcadm enable -r mongodb" in cmds
        assert "svcadm disable mongodb" in cmds
        assert "pkill -9 mongod" in cmds
        assert f.initiated, "replica set was not initiated"
        stdins = " ".join(a.get("in", "") for _h, _c, a in log
                          if isinstance(a.get("in"), str))
        assert "replSetName: jepsen" in stdins
    finally:
        f.stop()


# -- transfer protocol -------------------------------------------------------

def test_transfer_client_conserves_total():
    f = FakeMongo()
    try:
        t = {"mongo-conn-fn": lambda n: Conn("127.0.0.1", f.port),
             "accounts": [0, 1, 2], "total-amount": 30}
        c = mongodb_smartos.TransferClient().open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                         "process": 0})
        assert r["value"] == {0: 30, 1: 0, 2: 0}
        r = c.invoke(t, {"type": "invoke", "f": "transfer",
                         "value": {"from": 0, "to": 2, "amount": 7},
                         "process": 0})
        assert r["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                         "process": 0})
        assert r["value"] == {0: 23, 1: 0, 2: 7}
        # pendingTxns cleared after the two-phase dance
        docs = f.colls[("jepsen", "accts")]
        assert all(d["pendingTxns"] == [] for d in docs)
        txns = f.colls[("jepsen", "txns")]
        assert all(d["state"] == "done" for d in txns)
        c.close(t)
    finally:
        f.stop()


def test_transfer_transport_error_is_info():
    f = FakeMongo()
    t = {"mongo-conn-fn": lambda n: Conn("127.0.0.1", f.port)}
    c = mongodb_smartos.TransferClient().open(t, "n1")
    f.stop()
    r = c.invoke(t, {"type": "invoke", "f": "transfer",
                     "value": {"from": 0, "to": 1, "amount": 1},
                     "process": 0})
    assert r["type"] == "info"
    r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                     "process": 0})
    assert r["type"] == "fail"


# -- hermetic end-to-end ------------------------------------------------------

@pytest.mark.parametrize("workload", sorted(mongodb_smartos.WORKLOADS))
def test_hermetic_run(tmp_path, workload):
    f = FakeMongo()
    try:
        t = mongodb_smartos.mongodb_smartos_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "workload": workload,
            "rate": 300, "accounts": [0, 1, 2, 3],
            "time-limit": 3, "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["mongo-conn-fn"] = lambda n: Conn("127.0.0.1", f.port)
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        w = done["results"]["workload"]
        if workload == "transfer":
            # the by-hand two-phase protocol is NOT atomic: reads can
            # observe mid-transfer totals — the anomaly this reference
            # test exists to demonstrate. Any other error class would
            # mean the client or fake is broken.
            if w["valid?"] is not True:
                assert set(w.get("errors", {})) <= {"wrong-total"}, w
        else:
            assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()
