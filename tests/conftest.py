"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh so every sharding/pjit path is
exercised hermetically (no TPU needed), matching how the driver dry-runs the
multi-chip path. Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
