"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh so every sharding/pjit path is
exercised hermetically (no TPU needed), matching how the driver dry-runs the
multi-chip path.

Some session interpreters pre-import jax at startup (a sitecustomize hook
registers a real-TPU PJRT plugin and bakes ``jax_platforms="axon,cpu"``
into the already-imported config), so setting ``JAX_PLATFORMS`` in the
environment here is too late — we must also rewrite the live config.
``XLA_FLAGS`` is still read from the environment at CPU-client creation,
which is lazy, so setting it here is early enough.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "perf: performance-tier tests (reference perf_test.clj analog)")
