"""Tier-1 verification: the O(n) invariant screen + tier plumbing.

The contract under test (checker/screen.py, checker/linear.py,
checker/elle/__init__.py): clean histories pass the screen with
suspicion < 1; every history the full checker rejects in the labeled
matrix escalates (no false negatives at the screen boundary);
escalation is deterministic, priced through wgl.select_engine, and
surfaced through Compose / core.log_results / report / web alongside
the recovered/degraded trails without breaking older stored results.
"""

from __future__ import annotations

from jepsen_tpu import models
from jepsen_tpu.checker import Compose, linear, screen, synth
from jepsen_tpu.checker.elle import RWRegisterChecker

MODEL = models.cas_register()


def _hist(seed=13, n=400, conc=4, **kw):
    return synth.register_history(n, concurrency=conc, values=5,
                                  seed=seed, **kw)


# -- the register screen ----------------------------------------------------

def test_clean_register_histories_pass():
    for seed in (13, 21, 7, 45100):
        sc = screen.screen_history(MODEL, _hist(seed=seed))
        assert sc["valid?"] is True and sc["screened"]
        assert sc["suspicion"] < screen.ESCALATE_THRESHOLD, \
            (seed, sc["violations"][:2])


def test_corrupt_register_flags_phantom_read():
    sc = screen.screen_history(MODEL, synth.corrupt(_hist(), seed=3))
    assert sc["valid?"] is False
    assert sc["violations"][0]["check"] == "phantom-read"
    assert sc["suspicion"] >= screen.ESCALATE_THRESHOLD


def test_stale_read_detected_and_full_checker_agrees():
    ops = [
        {"type": "invoke", "f": "write", "value": 1, "process": 0},
        {"type": "ok", "f": "write", "value": 1, "process": 0},
        {"type": "invoke", "f": "write", "value": 2, "process": 1},
        {"type": "ok", "f": "write", "value": 2, "process": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 2},
        {"type": "ok", "f": "read", "value": 1, "process": 2},
    ]
    sc = screen.screen_history(models.register(), ops)
    assert [v["check"] for v in sc["violations"]] == ["stale-read"]
    from jepsen_tpu.checker import wgl
    assert wgl.analysis_tpu(models.register(), ops)["valid?"] is False


def test_concurrent_write_is_not_stale():
    # the overwriting 'write 2' is still in flight when the read
    # completes: observing 1 is legal, the screen must stay quiet
    ops = [
        {"type": "invoke", "f": "write", "value": 1, "process": 0},
        {"type": "ok", "f": "write", "value": 1, "process": 0},
        {"type": "invoke", "f": "write", "value": 2, "process": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 2},
        {"type": "ok", "f": "read", "value": 1, "process": 2},
        {"type": "ok", "f": "write", "value": 2, "process": 1},
    ]
    sc = screen.screen_history(models.register(), ops)
    assert sc["valid?"] is True


def test_crashed_write_softens_but_never_escalates_alone():
    h = _hist(seed=13, crash_rate=0.1)
    sc = screen.screen_history(MODEL, h)
    assert sc["valid?"] is True
    assert 0 < sc["suspicion"] < screen.ESCALATE_THRESHOLD
    assert sc["signals"]["crashed-mutators"] > 0


# -- counter / g-set screens ------------------------------------------------

def test_counter_clean_and_bounds_violation():
    hc = synth.counter_history(400, concurrency=4, seed=11)
    assert screen.screen_history(models.counter(), hc)["valid?"] \
        is True
    ops = [
        {"type": "invoke", "f": "add", "value": 5, "process": 0},
        {"type": "ok", "f": "add", "value": 5, "process": 0},
        {"type": "invoke", "f": "read", "value": None, "process": 1},
        {"type": "ok", "f": "read", "value": 100, "process": 1},
    ]
    sc = screen.screen_history(models.counter(), ops)
    assert sc["violations"][0]["check"] == "counter-bounds"
    assert sc["violations"][0]["hi"] == 5


def test_gset_lost_and_phantom_elements():
    hg = synth.gset_history(300, concurrency=4, seed=9)
    assert screen.screen_history(models.gset(), hg)["valid?"] is True
    ops = [
        {"type": "invoke", "f": "add", "value": 3, "process": 0},
        {"type": "ok", "f": "add", "value": 3, "process": 0},
        {"type": "invoke", "f": "read", "value": None, "process": 1},
        {"type": "ok", "f": "read", "value": [9], "process": 1},
    ]
    sc = screen.screen_history(models.gset(), ops)
    checks = sorted(v["check"] for v in sc["violations"])
    assert checks == ["set-lost", "set-phantom"]


# -- the wr screen ----------------------------------------------------------

def test_wr_clean_passes():
    sc = screen.screen_wr(synth.wr_history(300, concurrency=6, seed=5))
    assert sc["valid?"] is True and sc["signals"]["cyclic-sccs"] == 0


def test_wr_duplicate_write_flagged():
    txn = [["w", 0, 1]]
    ops = []
    for p in (0, 1):
        ops.append({"type": "invoke", "f": "txn", "value": txn,
                    "process": p})
        ops.append({"type": "ok", "f": "txn", "value": txn,
                    "process": p})
    sc = screen.screen_wr(ops)
    assert any(v["check"] == "duplicate-writes"
               for v in sc["violations"])


def test_wr_cycle_existence_is_exact():
    # a ww cycle with no single-pass anomaly: t0 writes x=1,y=2 after
    # reading the other's values — build edges via intra-txn order
    ops = [
        {"type": "invoke", "f": "txn", "value": None, "process": 0},
        {"type": "ok", "f": "txn",
         "value": [["r", 0, 2], ["w", 0, 1]], "process": 0},
        {"type": "invoke", "f": "txn", "value": None, "process": 1},
        {"type": "ok", "f": "txn",
         "value": [["r", 0, 1], ["w", 0, 2]], "process": 1},
    ]
    sc = screen.screen_wr(ops)
    assert sc["valid?"] is False
    assert any(v["check"] == "dependency-cycle"
               for v in sc["violations"])
    # the full checker classifies the same cycle
    from jepsen_tpu.checker.elle import wr
    full = wr.check(ops)
    assert full["valid?"] is False


# -- escalation decision ----------------------------------------------------

def test_sample_decision_is_deterministic():
    assert screen.sample_decision(123, 1.0) is True
    assert screen.sample_decision(123, 0.0) is False
    a = [screen.sample_decision(k, 0.3) for k in range(200)]
    assert a == [screen.sample_decision(k, 0.3) for k in range(200)]
    assert 20 < sum(a) < 120      # roughly the asked fraction


def test_should_escalate_scales_sampling_by_cost():
    sc = {"suspicion": 0.0, "op-count": 777}
    # find a key that samples at full strength
    esc_full, why = screen.should_escalate(sc, sample=1.0)
    assert esc_full and why == "sampled"
    # an astronomically expensive history suppresses sampling
    esc_costly, _ = screen.should_escalate(
        sc, sample=0.5, cost=screen.COST_REF * 1e9)
    assert esc_costly is False


def test_price_escalation_reports_engine_and_cost():
    p = screen.price_escalation(MODEL, _hist(n=100))
    assert p is not None
    assert p["family"] in ("dense", "sort") and p["cost"] > 0


# -- Linearizable tier plumbing --------------------------------------------

def test_tier_screen_pass_returns_screened_verdict():
    c = linear.Linearizable(MODEL, tier="screen", screen_sample=0.0)
    r = c.check({}, _hist(), {})
    assert r["screened"] and r["valid?"] is True and r["tier"] == 1
    assert "escalated" not in r and r["analyzer"] == "tier1-screen"


def test_tier_screen_suspicion_escalates_with_blame():
    c = linear.Linearizable(MODEL, tier="screen", screen_sample=0.0)
    r = c.check({}, synth.corrupt(_hist(), seed=3), {})
    assert r["valid?"] is False and "op-index" in r
    assert r["escalated"]["why"] == "suspicion"
    assert r["escalated"]["engine"]["family"] in ("dense", "sort")


def test_tier_screen_sampled_escalation():
    c = linear.Linearizable(MODEL, tier="screen", screen_sample=1.0)
    r = c.check({}, _hist(), {})
    assert r["valid?"] is True and r["escalated"]["why"] == "sampled"


def test_unscreenable_model_always_escalates():
    # a model family the screen has no invariants for must NEVER pass
    # on the sampled-audit path — a no-op screen escalates every time
    h = synth.mutex_history(60, concurrency=3, seed=5)
    sc = screen.screen_history(models.mutex(), h)
    assert sc["screenable"] is False
    esc, why = screen.should_escalate(sc, sample=0.0)
    assert esc and why == "unscreened-model"
    c = linear.Linearizable(models.mutex(), tier="screen",
                            screen_sample=0.0)
    r = c.check({}, h, {})
    assert "screened" not in r            # the full checker answered
    assert r["escalated"]["why"] == "unscreened-model"


def test_tier_from_test_map_and_default_full():
    r = linear.Linearizable(MODEL).check(
        {"tier": "screen", "screen-sample": 0.0}, _hist(), {})
    assert r.get("screened")
    r2 = linear.Linearizable(MODEL).check({}, _hist(n=100), {})
    assert "screened" not in r2 and "tier" not in r2


def test_screen_boundary_no_false_negatives():
    """The acceptance matrix: over labeled clean/anomalous histories,
    the screen never passes (without escalation) a history the full
    checker rejects."""
    from jepsen_tpu.checker import wgl
    matrix = [_hist(seed=s, n=200) for s in (13, 21, 7)]
    matrix += [synth.corrupt(h, seed=i + 3)
               for i, h in enumerate(matrix[:3])]
    for h in matrix:
        sc = screen.screen_history(MODEL, h)
        esc, _ = screen.should_escalate(sc, sample=0.0)
        full = wgl.analysis_tpu(MODEL, h, budget_s=60, explain=False)
        if full["valid?"] is False:
            assert esc, "screen passed a history the full checker " \
                        "rejects"


def test_rw_register_checker_tier():
    hw = synth.wr_history(200, concurrency=6, seed=5)
    rc = RWRegisterChecker()
    r = rc.check({"tier": "screen", "screen-sample": 0.0}, hw, {})
    assert r["screened"] and r["valid?"] is True
    r2 = rc.check({"tier": "screen", "screen-sample": 1.0}, hw, {})
    assert r2["escalated"]["why"] == "sampled"
    assert "anomalies" in r2        # the full result shape


# -- online integration -----------------------------------------------------

def test_maybe_online_adds_screen_targets():
    from jepsen_tpu.checker import streaming
    test = {"online": True, "tier": "screen",
            "checker": Compose({"lin": linear.Linearizable(MODEL),
                                "wr": RWRegisterChecker()})}
    oc = streaming.maybe_online(test)
    try:
        assert "screen-linear" in oc.targets
        assert "screen-wr" in oc.targets
    finally:
        oc.close()


def test_streamed_screen_result_is_reused():
    h = _hist(n=100)
    sc = screen.screen_history(MODEL, h)
    sc["marker"] = "from-stream"
    test = {"tier": "screen", "screen-sample": 0.0,
            "streamed-results": {"screen-linear": sc}}
    r = linear.Linearizable(MODEL).check(test, h, {})
    assert r.get("marker") == "from-stream"
    # a screen covering a different history is NOT reused
    test2 = {"tier": "screen", "screen-sample": 0.0,
             "streamed-results": {"screen-linear": dict(
                 sc, **{"history-len": 1})}}
    r2 = linear.Linearizable(MODEL).check(test2, h, {})
    assert "marker" not in r2


def test_screen_stream_violation_flag_for_abort():
    s = screen.ScreenStream(MODEL)
    for op in synth.corrupt(_hist(), seed=3).ops:
        s.feed(op)
        if s.violation:
            break
    assert s.violation


# -- surfacing --------------------------------------------------------------

def test_compose_surfaces_tier_outcomes():
    class _Returns:
        def __init__(self, result):
            self.result = result

        def __call__(self, test, hist, opts):
            return dict(self.result)

    r = Compose({
        "passed": _Returns({"valid?": True, "screened": True}),
        "bumped": _Returns({"valid?": True,
                            "escalated": {"why": "sampled"}}),
        "guarded": _Returns({"valid?": True,
                             "attested": {"steps": 1, "carry": 0}}),
        "legacy": _Returns({"valid?": True}),
    }).check({}, [], {})
    assert r["screened-checkers"] == ["passed"]
    assert r["escalated-checkers"] == ["bumped"]
    assert r["attested-checkers"] == ["guarded"]


def test_report_tier_line_and_legacy_results():
    from jepsen_tpu import report
    assert report.tier_line({}) == ""
    assert report.tier_line({"valid?": True}) == ""      # old results
    line = report.tier_line({"screened": True, "suspicion": 0.04})
    assert "screen passed" in line
    line = report.tier_line(
        {"escalated": {"why": "suspicion", "suspicion": 2.0,
                       "engine": {"family": "dense", "cost": 1e6}}})
    assert "escalated" in line and "dense" in line


def test_web_note_tier_suffixes_and_precedence():
    from jepsen_tpu import web
    assert web.recovery_note({"lin": {"valid?": True}}) == ""
    assert web.recovery_note(
        {"lin": {"valid?": True, "screened": True}}) == " (screened)"
    assert web.recovery_note(
        {"lin": {"escalated": {"why": "sampled"}}}) == " (escalated)"
    # fault outcomes outrank tier notes
    assert web.recovery_note(
        {"lin": {"screened": True},
         "o": {"recovered": {"faults": ["oom"]}}}) == " (recovered)"


def test_log_results_tier_summary(caplog):
    import logging

    from jepsen_tpu import core
    with caplog.at_level(logging.INFO, logger="jepsen_tpu.core"):
        core.log_results({"results": {
            "valid?": True,
            "screened-checkers": ["lin"],
            "attested-checkers": ["lin"],
            "lin": {"valid?": True, "screened": True,
                    "suspicion": 0.0}}})
    assert any("tier-1 verification" in m for m in caplog.messages)
    assert any("ABFT attestation" in m for m in caplog.messages)


def test_cli_exposes_tier_knobs():
    from jepsen_tpu import cli
    longs = {s["long"] for s in cli.test_opt_spec()}
    assert "--tier" in longs and "--screen-sample" in longs
