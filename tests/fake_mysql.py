"""An in-process MySQL-protocol server backed by sql_engine, standing in
for TiDB the way fake_etcd stands in for etcd: the suite's wire client
(`jepsen_tpu/suites/mysql_proto.py`) is exercised against the real
protocol framing, while the data layer stays hermetic and serializable.
"""

from __future__ import annotations

import socketserver

from netutil import NodelayHandler
import struct
import threading

from sql_engine import Engine, SQLError

COM_QUIT = 0x01
COM_QUERY = 0x03
COM_PING = 0x0E


def _lenenc(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little")
    return b"\xfe" + struct.pack("<Q", n)


def _lenenc_str(b: bytes) -> bytes:
    return _lenenc(len(b)) + b


class _Handler(NodelayHandler):

    def _send(self, payload: bytes):
        head = len(payload).to_bytes(3, "little") + bytes([self.seq])
        self.request.sendall(head + payload)
        self.seq = (self.seq + 1) % 256

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def _recv(self) -> bytes:
        head = self._recv_exact(4)
        n = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) % 256
        return self._recv_exact(n)

    def _ok(self, affected: int = 0):
        self._send(b"\x00" + _lenenc(affected) + _lenenc(0) +
                   struct.pack("<HH", 2, 0))

    def _err(self, code: int, msg: str):
        self._send(b"\xff" + struct.pack("<H", code) + b"#HY000" +
                   msg.encode())

    def _eof(self):
        self._send(b"\xfe" + struct.pack("<HH", 0, 2))

    def _resultset(self, rows, cols):
        self._send(_lenenc(len(cols)))
        for c in cols:
            cb = c.encode()
            self._send(_lenenc_str(b"def") + _lenenc_str(b"") +
                       _lenenc_str(b"t") + _lenenc_str(b"t") +
                       _lenenc_str(cb) + _lenenc_str(cb) +
                       b"\x0c" + struct.pack("<HIBHB", 33, 255, 0xFD, 0, 0)
                       + b"\x00\x00")
        self._eof()
        for row in rows:
            out = b""
            for v in row:
                out += b"\xfb" if v is None else _lenenc_str(
                    str(v).encode())
            self._send(out)
        self._eof()

    def handle(self):
        self.seq = 0
        srv: "FakeMySQLServer" = self.server  # type: ignore[assignment]
        session = srv.engine.session()
        try:
            # handshake v10, 20-byte salt, mysql_native_password
            salt = b"0123456789abcdefghij"
            greet = (b"\x0a" + b"5.7.25-TiDB-fake\0" +
                     struct.pack("<I", 1) + salt[:8] + b"\x00" +
                     struct.pack("<H", 0xF7FF) + b"\x21" +
                     struct.pack("<H", 2) + struct.pack("<H", 0x000F) +
                     bytes([21]) + b"\x00" * 10 + salt[8:] + b"\x00" +
                     b"mysql_native_password\0")
            self._send(greet)
            self._recv()  # handshake response; trust any auth
            self._ok()
            while True:
                pkt = self._recv()
                self.seq = 1
                cmd = pkt[0]
                if cmd == COM_QUIT:
                    return
                if cmd == COM_PING:
                    self._ok()
                    continue
                if cmd != COM_QUERY:
                    self._err(1047, f"unknown command {cmd}")
                    continue
                sql = pkt[1:].decode()
                if srv.fail_hook:
                    errc = srv.fail_hook(sql)
                    if errc:
                        self._err(*errc)
                        continue
                try:
                    rows, cols = session.execute(sql)
                except SQLError as e:
                    self._err(e.code, e.message)
                    continue
                if cols is None:
                    self._ok(rows)
                else:
                    self._resultset(rows, cols)
        except (ConnectionError, OSError):
            pass
        finally:
            session.abort()


class FakeMySQLServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, engine: Engine | None = None):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.engine = engine or Engine()
        # fail_hook(sql) -> (code, msg) to inject an error, or None
        self.fail_hook = None
        self.port = self.server_address[1]
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()
