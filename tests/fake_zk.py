"""An in-process fake ZooKeeper speaking the real wire protocol
(connect handshake + create/getData/setData/exists/delete), backed by a
lock-guarded dict of path -> (data, version). Exercises the suite's
jute client over actual TCP."""

from __future__ import annotations

import socketserver

from netutil import NodelayHandler
import struct
import threading

from jepsen_tpu.suites import zk_proto as z


class FakeZk:
    def __init__(self):
        self.nodes: dict[str, tuple[bytes, int]] = {}
        self.lock = threading.Lock()
        self.zxid = 0
        self.sessions = 0
        self.server: socketserver.ThreadingTCPServer | None = None

    def handle_op(self, op: int, r: z.Reader) -> tuple[int, bytes]:
        """-> (err, payload)"""
        with self.lock:
            self.zxid += 1
            if op == z.CREATE:
                path = r.string()
                data = r.buffer() or b""
                if path in self.nodes:
                    return z.NODEEXISTS, b""
                self.nodes[path] = (data, 0)
                return z.OK, z.enc_string(path)
            if op == z.GET_DATA:
                path = r.string()
                if path not in self.nodes:
                    return z.NONODE, b""
                data, version = self.nodes[path]
                return z.OK, z.enc_buffer(data) + self._stat(version,
                                                             len(data))
            if op == z.SET_DATA:
                path = r.string()
                data = r.buffer() or b""
                want = r.int()
                if path not in self.nodes:
                    return z.NONODE, b""
                _old, version = self.nodes[path]
                if want != -1 and want != version:
                    return z.BADVERSION, b""
                self.nodes[path] = (data, version + 1)
                return z.OK, self._stat(version + 1, len(data))
            if op == z.EXISTS:
                path = r.string()
                if path not in self.nodes:
                    return z.NONODE, b""
                data, version = self.nodes[path]
                return z.OK, self._stat(version, len(data))
            if op == z.DELETE:
                path = r.string()
                self.nodes.pop(path, None)
                return z.OK, b""
            return z.OK, b""

    def _stat(self, version: int, dlen: int) -> bytes:
        return (z.enc_long(1) + z.enc_long(self.zxid) + z.enc_long(0)
                + z.enc_long(0) + z.enc_int(version) + z.enc_int(0)
                + z.enc_int(0) + z.enc_long(0) + z.enc_int(dlen)
                + z.enc_int(0) + z.enc_long(self.zxid))

    def start(self) -> int:
        fake = self

        class Handler(NodelayHandler):

            def _recv_n(self, n):
                out = b""
                while len(out) < n:
                    chunk = self.request.recv(n - len(out))
                    if not chunk:
                        raise ConnectionError
                    out += chunk
                return out

            def _frame(self):
                (n,) = struct.unpack(">i", self._recv_n(4))
                return self._recv_n(n)

            def _send(self, payload):
                self.request.sendall(struct.pack(">i", len(payload))
                                     + payload)

            def handle(self):
                try:
                    r = z.Reader(self._frame())      # ConnectRequest
                    r.int(), r.long()
                    timeout = r.int()
                    with fake.lock:
                        fake.sessions += 1
                        sid = fake.sessions
                    self._send(z.enc_int(0) + z.enc_int(timeout)
                               + z.enc_long(sid)
                               + z.enc_buffer(b"\x00" * 16))
                    while True:
                        r = z.Reader(self._frame())
                        xid = r.int()
                        op = r.int()
                        if op == z.CLOSE:
                            return
                        if op == z.PING:
                            self._send(z.enc_int(-2) + z.enc_long(0)
                                       + z.enc_int(0))
                            continue
                        err, payload = fake.handle_op(op, r)
                        self._send(z.enc_int(xid)
                                   + z.enc_long(fake.zxid)
                                   + z.enc_int(err) + payload)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        return self.server.server_address[1]

    def stop(self):
        if self.server:
            self.server.shutdown()
