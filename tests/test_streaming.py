"""Streaming (online) verification: equivalence with the offline
checkers, chunked carry-resume identity, journal tail-follow, early
abort, and the end-to-end --online path.

The contract under test (checker/streaming.py): the online pipeline's
verdict on a history equals the offline verdict on the same history —
for both kernel families — because the incremental encoder emits a
byte-identical step stream and the chunked carry walk decides exactly
what the one-shot walk decides.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from jepsen_tpu import models, store
from jepsen_tpu.checker import streaming, synth, wgl
from jepsen_tpu.history import history


MODEL = models.cas_register()
DM = wgl.DEVICE_MODELS[MODEL.device_model]

# One sort shape (F=256, P=8, E=128) and one dense shape shared across
# the pipeline tests below, so tier-1 pays each kernel compile once.
CHUNK = 128
SLOTS = 8


def _valid_hist(n=400, conc=4, seed=7, crash_rate=0.0):
    return synth.register_history(n, concurrency=conc, values=5,
                                  crash_rate=crash_rate, seed=seed)


def _feed_all(s, hist):
    for op in hist.ops:
        s.feed(op)
    return s


# -- encoder identity -------------------------------------------------------

def test_encoder_stream_is_byte_identical_to_build_steps():
    h = synth.register_history(800, concurrency=5, values=5,
                               crash_rate=0.02, seed=7)
    ops = wgl.encode_ops_for_model(MODEL, h)
    p = wgl._bucket(wgl.required_slots(ops), lo=8)
    off = wgl.build_steps(ops, p)

    enc = streaming.StreamEncoder(DM.codec, DM.droppable, p)
    for op in h.ops:
        if isinstance(op.get("process"), int):
            enc.feed(op)
    enc.finish()
    rows = enc.take(10 ** 9)
    x = np.asarray(rows, np.int32)
    assert x.shape == off.x.shape
    assert (x == off.x).all()
    assert enc.steps_emitted == off.n


def test_encoder_resolves_crash_tail_like_encode_ops():
    # chop the final completions: the open tail must encode as
    # pending-forever :info rows, exactly as encode_ops does
    h = _valid_hist(300, seed=11)
    cut = [o for o in h.ops][:-7]
    h2 = history(cut)
    ops = wgl.encode_ops_for_model(MODEL, h2)
    p = wgl._bucket(wgl.required_slots(ops), lo=8)
    off = wgl.build_steps(ops, p)
    enc = streaming.StreamEncoder(DM.codec, DM.droppable, p)
    for op in h2.ops:
        if isinstance(op.get("process"), int):
            enc.feed(op)
    enc.finish()
    rows = enc.take(10 ** 9)
    assert (np.asarray(rows, np.int32) == off.x).all()


# -- chunked carry-resume: byte-identical verdict/config-counts -------------

def _one_crashed_write_hist():
    """Tiny history with a crashed (pending-forever) write so a chunk
    split can land strictly inside its pending window."""
    ops = []
    t = [0]

    def emit(o):
        o["time"] = t[0]
        t[0] += 1
        ops.append(o)

    emit({"type": "invoke", "f": "write", "value": 1, "process": 0})
    emit({"type": "ok", "f": "write", "value": 1, "process": 0})
    # the crashed write: invoked here, never completes
    emit({"type": "invoke", "f": "write", "value": 3, "process": 1})
    emit({"type": "info", "f": "write", "value": 3, "process": 1})
    for i in range(12):
        p = 2 + (i % 2)
        emit({"type": "invoke", "f": "read", "value": None, "process": p})
        # the crashed write of 3 legally linearizes between reads 5/6
        emit({"type": "ok", "f": "read", "value": 1 if i < 6 else 3,
              "process": p})
    return history(ops)


def _summaries_equal(a, b):
    for x, y in zip(a, b):
        assert np.asarray(x).tolist() == np.asarray(y).tolist()


@pytest.mark.parametrize("family", ["sort", "dense"])
def test_chunk_resume_byte_identical(family):
    import jax.numpy as jnp

    h = _one_crashed_write_hist()
    ops = wgl.encode_ops_for_model(MODEL, h)
    p = 4
    steps = wgl.build_steps(ops, p)
    E = 64
    padded = steps.pad_to(E)
    if family == "dense":
        k = wgl._dense_kernel("cas-register", -1, 8, p, E)
    else:
        k = wgl._kernel("cas-register", 64, p, E, None)
    x = jnp.asarray(padded.x)
    s0 = jnp.int32(MODEL.device_state())
    import jax
    one_shot = jax.device_get(k.check(x, jnp.int32(steps.n), s0))

    def pad_chunk(rows):
        buf = np.zeros((E, padded.x.shape[1]), np.int32)
        buf[:, steps.w] = -1
        buf[:, steps.w + 2:] = -1
        buf[:len(rows)] = rows
        return jnp.asarray(buf)

    # every split point — including splits that land while the crashed
    # write is pending (it pends from step 1 to the very end)
    for split in range(steps.n + 1):
        carry = k.init_carry(s0)
        carry = k.check_stream_chunk(pad_chunk(padded.x[:split]),
                                     jnp.int32(split), carry)
        carry = k.check_stream_chunk(
            pad_chunk(padded.x[split:steps.n]),
            jnp.int32(steps.n - split), carry)
        _summaries_equal(jax.device_get(k.summarize(carry)), one_shot)


# -- online pipeline == offline verdicts ------------------------------------

def test_stream_valid_matches_offline_sort():
    h = _valid_hist()
    r = streaming.stream_check(MODEL, h, chunk_entries=CHUNK,
                               slots=SLOTS)
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] is True and a["valid?"] is True
    assert r["analyzer"] == "tpu-wgl-streaming"
    assert r["chunks"] >= 2
    assert r["op-count"] == a["op-count"]


def test_stream_invalid_matches_offline_sort_and_names_culprit():
    h = synth.corrupt(_valid_hist(), seed=3)
    r = streaming.stream_check(MODEL, h, chunk_entries=CHUNK,
                               slots=SLOTS)
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] is False and a["valid?"] is False
    assert r.get("op-index") == a.get("op-index")
    assert r["op"]["value"] == 10 ** 6


def test_stream_valid_matches_offline_dense():
    h = _valid_hist(seed=13)
    r = streaming.stream_check(MODEL, h, chunk_entries=CHUNK,
                               slots=SLOTS, engine="dense",
                               state_range=(-1, 4))
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] is True and a["valid?"] is True
    assert r["analyzer"] == "tpu-wgl-dense-streaming"


def test_stream_dense_invalid_in_range_matches_offline():
    # an in-range stale read: the dense table must catch it without
    # any range escape
    h = _valid_hist(seed=17)
    bad = None
    for i, o in enumerate(h.ops):
        if o["type"] == "ok" and o["f"] == "read" \
                and o.get("value") is not None and i > 50:
            ops2 = [dict(x) for x in h.ops]
            ops2[i]["value"] = (ops2[i]["value"] + 2) % 5
            cand = history(ops2)
            if wgl.analysis_tpu(MODEL, cand)["valid?"] is False:
                bad = cand
                break
    assert bad is not None, "could not build an in-range violation"
    r = streaming.stream_check(MODEL, bad, chunk_entries=CHUNK,
                               slots=SLOTS, engine="dense",
                               state_range=(-1, 4))
    assert r["valid?"] is False
    assert r["analyzer"] == "tpu-wgl-dense-streaming"


def test_stream_dense_range_escape_falls_back_to_sort():
    # corrupt() writes a read of 10**6 — far outside the declared
    # range; the stream must rebuild onto the sort kernel, not return
    # an unsound dense verdict
    h = synth.corrupt(_valid_hist(seed=19), seed=5)
    r = streaming.stream_check(MODEL, h, chunk_entries=CHUNK,
                               slots=SLOTS, engine="dense",
                               state_range=(-1, 4))
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] is False and a["valid?"] is False
    assert r["analyzer"] == "tpu-wgl-streaming"   # downgraded


def test_stream_crash_tail_matches_offline():
    h = history([o for o in _valid_hist(seed=23).ops][:-9])
    r = streaming.stream_check(MODEL, h, chunk_entries=CHUNK,
                               slots=SLOTS)
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] == a["valid?"] is True


def test_stream_slot_overflow_rebuilds_and_agrees():
    h = _valid_hist(n=300, conc=12, seed=29)
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=8)
    _feed_all(s, h)
    assert s.p > 8          # the rebuild happened
    r = s.finish()
    a = wgl.analysis_tpu(MODEL, h)
    assert r["valid?"] == a["valid?"] is True


def test_stream_early_abort_detects_mid_feed():
    h = _valid_hist(n=1200, conc=4, seed=31)
    # plant the violation at ~25% so chunks keep flowing afterwards
    ops = [dict(o) for o in h.ops]
    for i, o in enumerate(ops):
        if i > len(ops) // 4 and o["type"] == "ok" \
                and o["f"] == "read":
            o["value"] = 10 ** 6
            break
    bad = history(ops)
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS)
    fed = 0
    for op in bad.ops:
        s.feed(op)
        fed += 1
        if s.violation:
            break
    assert s.violation and fed < len(bad.ops)
    r = s.finish()
    assert r["valid?"] is False
    assert r["violation-at-op"] == s.violation_at_op <= fed


# -- streaming elle (wr) ----------------------------------------------------

def _wr_ok(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn,
             "process": process, "time": t},
            {"type": "ok", "f": "txn", "value": txn,
             "process": process, "time": t + 1}]


def _wr_fail(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn,
             "process": process, "time": t},
            {"type": "fail", "f": "txn", "value": txn,
             "process": process, "time": t + 1}]


def _wr_parity(h):
    from jepsen_tpu.checker.elle import wr
    s = streaming.WrStream()
    for op in h.ops:
        s.feed(op)
    r = s.finish()
    a = wr.check(h)
    assert r["valid?"] == a["valid?"]
    assert r["anomaly-types"] == a["anomaly-types"]
    assert r["txn-count"] == a["txn-count"]
    return r


def test_wr_stream_parity_on_workload_history():
    _wr_parity(synth.wr_history(600, seed=45100))


def test_wr_stream_parity_fixtures():
    # G1c cycle
    _wr_parity(history(
        _wr_ok(0, [["w", "x", 1], ["r", "y", 1]], 0)
        + _wr_ok(1, [["w", "y", 1], ["r", "x", 1]], 2)))
    # G-single via a nil read
    _wr_parity(history(
        _wr_ok(0, [["w", "x", 1], ["w", "y", 1]], 0)
        + _wr_ok(1, [["r", "y", 1], ["r", "x", None]], 2)))
    # internal + G1b
    _wr_parity(history(
        _wr_ok(0, [["w", "x", 1], ["w", "x", 2]], 0)
        + _wr_ok(1, [["r", "x", 1]], 2)))


def test_wr_stream_late_arrivals_resolve():
    # the read lands BEFORE its writer completes, and a failed write is
    # read before the :fail arrives — both must resolve through the
    # pending indexes
    g1a_late = history(
        _wr_ok(1, [["r", "x", 9]], 0)
        + _wr_fail(0, [["w", "x", 9]], 2))
    r = _wr_parity(g1a_late)
    assert "G1a" in r["anomaly-types"]

    wr_late = history(
        _wr_ok(1, [["r", "x", 1], ["w", "y", 1]], 0)
        + _wr_ok(0, [["w", "x", 1], ["r", "y", 1]], 2))
    r2 = _wr_parity(wr_late)
    assert r2["valid?"] is False


# -- streamed-result reuse guards -------------------------------------------

def test_streamed_reuse_guards():
    from jepsen_tpu.checker.elle import RWRegisterChecker
    from jepsen_tpu.checker.linear import Linearizable

    h = history(_wr_ok(0, [["w", "x", 1]], 0)
                + _wr_ok(1, [["r", "x", 1]], 2))
    s = streaming.WrStream()
    for op in h.ops:
        s.feed(op)
    r = s.finish()
    test = {"streamed-results": {"elle-wr": r}}
    # same question: reused verbatim
    plain = RWRegisterChecker()
    assert plain.check(test, h, {}) == dict(r)
    # a sibling with additional graphs must NOT adopt the plain result
    rt = RWRegisterChecker(additional_graphs=("realtime",))
    assert "streamed" not in rt.check(test, h, {})
    # ... nor one asking about different anomalies
    narrow = RWRegisterChecker(anomalies=("G1a",))
    assert "streamed" not in narrow.check(test, h, {})

    # Linearizable: a different model never adopts another's verdict
    hr = _valid_hist(n=40, conc=2, seed=37)
    lr = {"valid?": True, "streamed": True, "model": repr(MODEL),
          "history-len": len(hr.client_ops())}
    ltest = {"streamed-results": {"linear": lr}}
    same = Linearizable(MODEL, "host")
    other = Linearizable(models.cas_register(0), "host")
    assert same.check(ltest, hr, {}).get("streamed") is True
    assert other.check(ltest, hr, {}).get("streamed") is None


def test_dense_caps_raise_at_construction():
    with pytest.raises(ValueError):
        streaming.WglStream(MODEL, engine="dense",
                            state_range=(-1, 4), slots=32)
    # 'auto' downgrades to the sort engine instead of declining the
    # whole online pipeline (a state-range hint at high concurrency
    # must not cost the user streaming altogether)
    s = streaming.WglStream(MODEL, engine="auto",
                            state_range=(-1, 4), slots=32)
    assert s.engine == "sort"


# -- journal subscribe / tail-follow ----------------------------------------

def test_journal_subscribe_feeds_ops_and_drops_broken(tmp_path):
    j = store.Journal(str(tmp_path / "journal.jsonl"))
    seen = []
    unsub = j.subscribe(seen.append)

    def broken(op):
        raise RuntimeError("boom")
    j.subscribe(broken)
    j.append({"type": "invoke", "f": "w", "process": 0})
    j.append({"type": "ok", "f": "w", "process": 0})
    j.close()
    assert len(seen) == 2
    unsub()
    assert j._subs == []    # the broken one was dropped too


def test_journal_tail_buffers_torn_line(tmp_path):
    p = str(tmp_path / "journal.jsonl")
    tail = store.JournalTail(p)
    assert tail.poll() == []          # not created yet
    with open(p, "w") as fh:
        fh.write(json.dumps({"i": 1}) + "\n")
        fh.write('{"i": 2, "val')     # torn mid-write
        fh.flush()
        assert tail.poll() == [{"i": 1}]
        assert tail.poll() == []      # torn tail stays buffered
        fh.write('ue": "x"}\n')       # the rest lands
        fh.flush()
        assert tail.poll() == [{"i": 2, "value": "x"}]
    with open(p, "a") as fh:
        fh.write("{corrupt}\n")
    with pytest.raises(ValueError):
        tail.poll()


# -- end-to-end: core.run --online ------------------------------------------

def _atom_test(tmp_path, n=400, **kw):
    import random

    from jepsen_tpu import generator as gen, testkit
    from jepsen_tpu.checker import linearizable

    state = testkit.AtomState()
    rng = random.Random(45100)
    t = testkit.noop_test()
    t["ssh"] = {"dummy": True}
    t["store-dir"] = str(tmp_path / "store")
    t.update({
        "name": "online smoke",
        "db": testkit.atom_db(state),
        "client": testkit.atom_client(state, latency_s=0.0),
        "concurrency": 5,
        # AtomDB.setup zeroes the cell, so the model starts at 0
        "checker": linearizable(models.cas_register(0)),
        "online": True,
        "online-chunk-entries": CHUNK,
        "generator": gen.clients(gen.limit(n, gen.mix([
            lambda: {"f": "read"},
            lambda: {"f": "write", "value": rng.randint(0, 4)},
            lambda: {"f": "cas", "value": [rng.randint(0, 4),
                                           rng.randint(0, 4)]},
        ]))),
    })
    t.update(kw)
    return t


def test_core_run_online_streams_and_reuses_result(tmp_path):
    from jepsen_tpu import core

    t = core.run(_atom_test(tmp_path))
    sr = t["streamed-results"]["linear"]
    assert sr["valid?"] is True
    assert sr["streamed"] is True
    # analyze() reused the streamed verdict instead of re-checking
    assert t["results"]["valid?"] is True
    assert t["results"].get("streamed") is True
    assert t["results"]["analyzer"].startswith("tpu-wgl")
    # ... and the journal fed the stream (a journal existed: named test)
    assert (tmp_path / "store").exists()


from jepsen_tpu import client as jclient  # noqa: E402


class _LyingClient(jclient.Client):
    """Returns impossible reads after a warm-up — the violation the
    online checker must catch mid-run."""

    def __init__(self, state, after):
        from jepsen_tpu import testkit
        self.inner = testkit.atom_client(state, latency_s=0.0005)
        self.after = after
        self.count = [0]

    def open(self, test, node):
        c = _LyingClient.__new__(_LyingClient)
        c.inner = self.inner.open(test, node)
        c.after = self.after
        c.count = self.count
        return c

    def setup(self, test):
        self.inner.setup(test)

    def invoke(self, test, op):
        out = self.inner.invoke(test, op)
        self.count[0] += 1
        if self.count[0] > self.after and op["f"] == "read" \
                and out["type"] == "ok":
            out = dict(out)
            out["value"] = 10 ** 6
        return out

    def teardown(self, test):
        self.inner.teardown(test)

    def close(self, test):
        self.inner.close(test)


def test_core_run_abort_on_violation(tmp_path):
    from jepsen_tpu import core, testkit

    # pre-warm the exact kernel shape the online checker will use, so
    # the abort races the (fast) run with a hot compile cache
    streaming.stream_check(MODEL, _valid_hist(n=60, conc=4, seed=3),
                           chunk_entries=CHUNK, slots=16)
    state = testkit.AtomState()
    n = 20000
    t = _atom_test(tmp_path, n=n, name="abort on violation",
                   client=_LyingClient(state, after=150),
                   db=testkit.atom_db(state))
    t["abort-on-violation"] = True
    done = core.run(t)
    assert done.get("aborted-on-violation") is True
    assert len(done["history"]) < 2 * n   # the run stopped early
    assert done["results"]["valid?"] is False


# -- CLI: --online / --abort-on-violation / compile cache -------------------

def test_cli_online_end_to_end(tmp_path, monkeypatch):
    from jepsen_tpu import cli

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)

    def test_fn(options):
        t = _atom_test(tmp_path, n=120)
        t["name"] = "cli online"
        t["store-dir"] = options["store-dir"]
        # the CLI flags must have reached the test map
        assert options["online"] is True
        assert options["abort-on-violation"] is True
        t["online"] = options["online"]
        t["abort-on-violation"] = options["abort-on-violation"]
        return t

    cmds = cli.single_test_cmd({"test_fn": test_fn})
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["test", "--no-ssh", "--online",
                       "--abort-on-violation",
                       "--store-dir", str(tmp_path / "store")])
    assert e.value.code == 0
    # the persistent compilation cache satellite: env-gated enablement
    import os
    assert os.environ["JAX_COMPILATION_CACHE_DIR"].endswith(
        ".jax_cache")
    stored = store.load_test(str(tmp_path / "store" / "latest"))
    assert stored["results"]["valid?"] is True
    assert stored["results"].get("streamed") is True
