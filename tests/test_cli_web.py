"""CLI + web UI tests: option parsing/post-processing parity with
cli.clj, end-to-end `test` command runs over the dummy remote, exit
codes, and the store browser."""

import json
import os
import urllib.request

import pytest

from jepsen_tpu import checker, cli, generator as gen
from jepsen_tpu import repl, report, testkit, web


# -- option post-processing -------------------------------------------------

def parse(argv, extra_spec=None):
    p = cli.build_parser("test",
                         cli.merge_opt_specs(cli.test_opt_spec(),
                                             extra_spec or []))
    return vars(p.parse_args(argv))


def test_defaults():
    o = cli.test_opt_fn(parse([]))
    assert o["nodes"] == cli.DEFAULT_NODES
    assert o["concurrency"] == 5  # 1n * 5 nodes
    assert o["ssh"]["dummy"] is False
    assert o["ssh"]["username"] == "root"
    assert o["time-limit"] == 60
    assert o["test-count"] == 1


def test_concurrency_multiplier():
    o = cli.test_opt_fn(parse(["--concurrency", "3n"]))
    assert o["concurrency"] == 15
    o = cli.test_opt_fn(parse(["--concurrency", "7"]))
    assert o["concurrency"] == 7
    with pytest.raises(ValueError):
        cli.test_opt_fn(parse(["--concurrency", "x3"]))


def test_node_flags_override_default():
    o = cli.test_opt_fn(parse(["-n", "a", "-n", "b"]))
    # repeated -n extends argparse's default list; the post-processing
    # must drop the default when explicit nodes were given
    assert o["nodes"] == ["a", "b"]


def test_nodes_list():
    o = cli.test_opt_fn(parse(["--nodes", "a,b, c"]))
    assert o["nodes"] == ["a", "b", "c"]


def test_nodes_file(tmp_path):
    f = tmp_path / "nodes"
    f.write_text("x1\nx2\n\nx3\n")
    o = cli.test_opt_fn(parse(["--nodes-file", str(f)]))
    assert o["nodes"] == ["x1", "x2", "x3"]


def test_ssh_opts():
    o = cli.test_opt_fn(parse(["--no-ssh", "--username", "admin",
                               "--ssh-private-key", "/k"]))
    assert o["ssh"] == {"dummy": True, "username": "admin",
                       "password": "root",
                       "strict-host-key-checking": False,
                       "private-key-path": "/k"}


def test_merge_opt_specs_prefers_latter():
    spec = cli.merge_opt_specs(cli.test_opt_spec(),
                               [cli.opt("--time-limit", type=int,
                                        default=10)])
    p = cli.build_parser("t", spec)
    assert vars(p.parse_args([]))["time_limit"] == 10


def test_invalid_args_exit_254():
    with pytest.raises(SystemExit) as e:
        cli.run({"test": {"opt_spec": cli.test_opt_spec()}},
                ["test", "--bogus-flag"])
    assert e.value.code == 254


def test_unknown_command_exits_254(capsys):
    with pytest.raises(SystemExit) as e:
        cli.run({"test": {}}, ["wat"])
    assert e.value.code == 254
    assert "Commands:" in capsys.readouterr().out


def test_internal_error_exits_255():
    def boom(opts):
        raise RuntimeError("nope")
    with pytest.raises(SystemExit) as e:
        cli.run({"test": {"opt_spec": [], "run": boom}}, ["test"])
    assert e.value.code == 255


# -- single_test_cmd end to end ---------------------------------------------

def make_test_fn(tmp_path, valid=True, state_box=None):
    def test_fn(opts):
        state = testkit.AtomState()
        if state_box is not None:
            state_box.append(state)
        chk = checker.unbridled_optimism() if valid else \
            (lambda test, hist, o: {"valid?": False})
        return {
            **{k: v for k, v in opts.items()
               if k in ("nodes", "concurrency", "ssh", "store-dir",
                        "leave-db-running?", "logging")},
            "name": "cli-test",
            "store-dir": str(tmp_path / "store"),
            "db": testkit.atom_db(state),
            "client": testkit.atom_client(state, latency_s=0.0),
            "checker": chk,
            "generator": gen.clients(
                gen.limit(20, gen.repeat({"f": "read"}))),
        }
    return test_fn


def test_single_test_cmd_ok(tmp_path):
    cmds = cli.single_test_cmd({"test_fn": make_test_fn(tmp_path)})
    assert set(cmds) == {"test", "analyze"}
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["test", "--no-ssh", "--concurrency", "2"])
    assert e.value.code == 0
    assert os.path.isdir(tmp_path / "store" / "cli-test")


def test_single_test_cmd_invalid_exits_1(tmp_path):
    cmds = cli.single_test_cmd({"test_fn": make_test_fn(tmp_path,
                                                        valid=False)})
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["test", "--no-ssh"])
    assert e.value.code == 1


def test_analyze_command(tmp_path):
    test_fn = make_test_fn(tmp_path)
    cmds = cli.single_test_cmd({"test_fn": test_fn})
    with pytest.raises(SystemExit):
        cli.run(cmds, ["test", "--no-ssh"])
    # analyze re-checks the stored history without re-running
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["analyze", "--no-ssh"])
    assert e.value.code == 0


def test_test_all_cmd(tmp_path):
    test_fn = make_test_fn(tmp_path)

    def tests_fn(opts):
        return [test_fn(opts), test_fn(opts)]

    cmds = cli.test_all_cmd({"tests_fn": tests_fn})
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["test-all", "--no-ssh"])
    assert e.value.code == 0


def test_test_all_failure_code(tmp_path):
    ok_fn = make_test_fn(tmp_path)
    bad_fn = make_test_fn(tmp_path, valid=False)

    cmds = cli.test_all_cmd(
        {"tests_fn": lambda o: [ok_fn(o), bad_fn(o)]})
    with pytest.raises(SystemExit) as e:
        cli.run(cmds, ["test-all", "--no-ssh"])
    assert e.value.code == 1


# -- web UI -----------------------------------------------------------------

@pytest.fixture
def populated_store(tmp_path):
    test_fn = make_test_fn(tmp_path)
    cmds = cli.single_test_cmd({"test_fn": test_fn})
    with pytest.raises(SystemExit):
        cli.run(cmds, ["test", "--no-ssh"])
    return str(tmp_path / "store")


def test_home_page(populated_store):
    page = web.home_page(populated_store)
    assert "cli-test" in page
    assert web.COLORS["ok"] in page  # valid run renders blue


def test_valid_colors():
    assert web.valid_color(True) == web.COLORS["ok"]
    assert web.valid_color(False) == web.COLORS["fail"]
    assert web.valid_color("unknown") == web.COLORS["info"]
    assert web.valid_color("incomplete") == web.COLORS[None]


def test_web_server_end_to_end(populated_store):
    server = web.serve({"host": "127.0.0.1", "port": 0,
                        "store-dir": populated_store})
    port = server.server_address[1]
    try:
        def get(path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}") as r:
                    return (r.status, r.headers.get("Content-Type"),
                            r.read())
            except urllib.error.HTTPError as e:
                return e.code, e.headers.get("Content-Type"), b""

        status, ctype, body = get("/")
        assert status == 200 and b"cli-test" in body

        status, ctype, body = get("/files/cli-test")
        assert status == 200 and b"latest" in body

        runs = [d for d in os.listdir(
            os.path.join(populated_store, "cli-test"))
            if not d.startswith("latest")]
        run = runs[0]
        status, ctype, body = get(f"/files/cli-test/{run}/results.json")
        assert status == 200
        assert json.loads(body)["valid?"] is True

        status, ctype, body = get(f"/files/cli-test/{run}/jepsen.log")
        assert ctype == "text/plain; charset=utf-8"

        status, _, body = get("/?q=cli&valid=true&sort=name&dir=asc")
        assert status == 200 and b"cli-test" in body
        status, _, body = get("/?q=no-such-test")
        assert status == 200 and b"cli-test" not in body

        status, ctype, body = get(f"/files/cli-test/{run}.zip")
        assert status == 200 and ctype == "application/zip"
        assert body[:2] == b"PK"

        # path traversal is refused
        status, _, _ = get("/files/..%2f..%2fetc")
        assert status in (403, 404)
    finally:
        server.shutdown()


# -- report / repl ----------------------------------------------------------

def test_report_to(tmp_path, capsys):
    p = str(tmp_path / "out.txt")
    with report.to(p):
        print("hello report")
    assert "hello report" in open(p).read()
    assert "hello report" in capsys.readouterr().out


def test_repl_latest(populated_store):
    t = repl.latest_test(populated_store)
    assert t["name"] == "cli-test"
    assert len(t["history"]) == 40
    assert t["results"]["valid?"] is True
    # post-hoc re-analysis with a different checker
    re = repl.recheck(dict(t, **{"store-dir": populated_store}),
                      checker.stats())
    assert re["results"]["valid?"] is True


def test_duplicate_nodes_rejected_early():
    with pytest.raises(ValueError, match="more than once"):
        cli.parse_nodes({"node": ["n1", "n2", "n1"]})
    with pytest.raises(SystemExit) as e:
        cli.run({"test": {"opt_spec": cli.test_opt_spec(),
                          "opt_fn": cli.test_opt_fn,
                          "run": lambda o: None}},
                ["test", "--node", "a", "--node", "a"])
    assert e.value.code == 254


def test_select_tests_search_filter_sort():
    mk = lambda name, t, v: {"name": name, "start-time": t,  # noqa: E731
                             "results": {"valid?": v}}
    ts = [mk("etcd", "2026-01-02", True),
          mk("etcd", "2026-01-01", False),
          mk("zookeeper", "2026-01-03", "unknown")]
    # default: newest first
    assert [t["start-time"] for t in web.select_tests(ts, {})] == \
        ["2026-01-03", "2026-01-02", "2026-01-01"]
    # search narrows by name substring
    assert all(t["name"] == "etcd"
               for t in web.select_tests(ts, {"q": "etc"}))
    # validity filter matches stringified valid?
    assert [t["start-time"]
            for t in web.select_tests(ts, {"valid": "false"})] == \
        ["2026-01-01"]
    assert [t["start-time"]
            for t in web.select_tests(ts, {"valid": "unknown"})] == \
        ["2026-01-03"]
    # explicit sort by name ascending
    got = web.select_tests(ts, {"sort": "name", "dir": "asc"})
    assert [t["name"] for t in got] == ["etcd", "etcd", "zookeeper"]
