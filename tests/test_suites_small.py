"""Small-suite sweep tests: logcabin, robustirc, mysql-cluster,
rethinkdb — DB command generation, client semantics against fakes, and
hermetic end-to-end runs."""

import re

import jepsen_tpu.db
import jepsen_tpu.os_
from fake_mysql import FakeMySQLServer
from fake_rethinkdb import FakeRethinkDB
from fake_robustirc import FakeRobustIRC
from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.independent import ktuple
from jepsen_tpu.suites import (logcabin, mysql_cluster, rethinkdb,
                               robustirc, suite)
from jepsen_tpu.suites.mysql_proto import Conn as MyConn
from jepsen_tpu.suites.reql_proto import Conn as ReqlConn


def test_suite_registry():
    assert suite("logcabin") is logcabin
    assert suite("robustirc") is robustirc
    assert suite("mysql-cluster") is mysql_cluster
    assert suite("rethinkdb") is rethinkdb


def _with_n1(remote, fn):
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            return fn()


# -- logcabin ----------------------------------------------------------------

def test_logcabin_db_commands():
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"]}
    _with_n1(remote, lambda: (logcabin.db().setup(test, "n1"),
                              logcabin.db().teardown(test, "n1")))
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "git clone --depth 1" in cmds
    assert "scons" in cmds
    assert "--bootstrap" in cmds            # first node bootstraps
    assert "/root/Reconfigure" in cmds and "set" in cmds
    stdins = " ".join(a.get("in", "") for _h, _c, a in log
                      if isinstance(a.get("in"), str))
    assert "serverId = 1" in stdins


class _LogCabinSim:
    """A register behind scripted TreeOps command responses."""

    def __init__(self):
        self.value = "null"

    def __call__(self, context, action):
        cmd = action.get("cmd", "")
        stdin = action.get("in", "")
        m = re.search(r"-p /jepsen:(\S+) ", cmd)
        if m:  # cas
            if m.group(1) != self.value:
                return {"exit": 1, "err": (
                    f"Exiting due to LogCabin::Client::Exception: "
                    f"Path '/jepsen' has value '{self.value}', not "
                    f"'{m.group(1)}' as required")}
            self.value = stdin
            return {"exit": 0, "out": ""}
        if " write /jepsen" in cmd:
            self.value = stdin
            return {"exit": 0, "out": ""}
        if " read /jepsen" in cmd:
            return {"exit": 0, "out": self.value}
        return {"exit": 0, "out": ""}


def test_logcabin_hermetic_run(tmp_path):
    sim = _LogCabinSim()
    remote = dummy.remote(responses={r"TreeOps": sim})
    t = logcabin.logcabin_test({
        "nodes": ["n1", "n2", "n3"], "concurrency": 3,
        "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
        "faults": ["none"]})
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["remote"] = remote
    t["store-dir"] = str(tmp_path / "store")
    done = core.run(t)
    assert done["results"]["valid?"] is True, done["results"]
    oks = sum(1 for o in done["history"] if o.get("type") == "ok")
    assert oks > 10


def test_logcabin_cas_mismatch_is_fail():
    sim = _LogCabinSim()
    sim.value = "3"
    remote = dummy.remote(responses={r"TreeOps": sim})
    test = {"nodes": ["n1"],
            "sessions": {"n1": remote.connect({"host": "n1"})}}
    c = logcabin.CASClient().open(test, "n1")
    r = c.invoke(test, {"type": "invoke", "f": "cas", "value": (4, 5),
                        "process": 0})
    assert r["type"] == "fail" and r["error"] == "cas-mismatch"
    r = c.invoke(test, {"type": "invoke", "f": "cas", "value": (3, 5),
                        "process": 0})
    assert r["type"] == "ok"
    r = c.invoke(test, {"type": "invoke", "f": "read", "value": None,
                        "process": 0})
    assert r["type"] == "ok" and r["value"] == 5


# -- robustirc ---------------------------------------------------------------

def test_robustirc_session_and_topics():
    f = FakeRobustIRC()
    try:
        t = {"irc-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = robustirc.SetClient().open(t, "n1")
        for v in (1, 2, 3):
            r = c.invoke(t, {"type": "invoke", "f": "add", "value": v,
                             "process": 0})
            assert r["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                         "process": 0})
        assert r["type"] == "ok" and r["value"] == [1, 2, 3]
    finally:
        f.stop()


def test_robustirc_hermetic_run(tmp_path):
    f = FakeRobustIRC()
    try:
        t = robustirc.robustirc_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["irc-url-fn"] = lambda n: f"http://127.0.0.1:{f.port}"
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_robustirc_db_commands():
    log = []
    remote = dummy.remote(log=log, responses={r"dpkg-query": "ii"})
    test = {"nodes": ["n1", "n2"]}
    _with_n1(remote, lambda: robustirc.db().setup(test, "n1"))
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "go get -u github.com/robustirc/robustirc" in cmds
    assert "-singlenode" in cmds            # n1 bootstraps the network
    assert "start-stop-daemon" in cmds


# -- mysql-cluster -----------------------------------------------------------

def test_mysql_cluster_config_generation():
    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"]}
    conf = mysql_cluster.nodes_conf(test)
    assert "[ndb_mgmd]\nNodeId=1\nhostname=n1" in conf
    assert "NodeId=14" in conf              # ndbd ids 11+, 4 nodes max
    assert "NodeId=15\nhostname" not in conf.split("[mysqld]")[0]
    assert "[mysqld]\nNodeId=21\nhostname=n1" in conf
    assert len(re.findall(r"\[ndbd\]", conf)) == 4


def test_mysql_cluster_db_commands():
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"],
            "deb-url": "file:///tmp/mysql-cluster.deb"}
    _with_n1(remote, lambda: (mysql_cluster.db().setup(test, "n1"),
                              mysql_cluster.db().teardown(test, "n1")))
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "dpkg -i --force-confask --force-confnew" in cmds
    assert "ndb_mgmd --ndb-nodeid=1" in cmds
    assert "ndbd --ndb-nodeid=11" in cmds
    assert "mysqld_safe --defaults-file=/etc/my.cnf" in cmds


def test_mysql_cluster_hermetic_run(tmp_path):
    f = FakeMySQLServer()
    try:
        t = mysql_cluster.mysql_cluster_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["sql-conn-fn"] = lambda n: MyConn("127.0.0.1", f.port)
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- rethinkdb ---------------------------------------------------------------

def test_reql_roundtrip():
    from jepsen_tpu.suites import reql_proto as r
    f = FakeRethinkDB()
    try:
        c = ReqlConn("127.0.0.1", f.port)
        c.run(r.db_create("jepsen"))
        c.run(r.table_create("jepsen", "cas"))
        res = c.run(r.insert(r.table("jepsen", "cas"),
                             {"id": 0, "val": 3}, conflict="update"))
        assert res["errors"] == 0
        v = c.run(r.default(
            r.get_field(r.get(r.table("jepsen", "cas"), 0), "val"),
            None))
        assert v == 3
        # cas via branch-on-eq update
        res = c.run(r.update(
            r.get(r.table("jepsen", "cas"), 0),
            r.func(r.branch(
                r.eq(r.get_field(r.var(1), "val"), 3),
                {"val": 4}, r.error("abort")))))
        assert res["replaced"] == 1
        res = c.run(r.update(
            r.get(r.table("jepsen", "cas"), 0),
            r.func(r.branch(
                r.eq(r.get_field(r.var(1), "val"), 9),
                {"val": 5}, r.error("abort")))))
        assert res["errors"] == 1
        c.close()
    finally:
        f.stop()


def test_rethinkdb_client_semantics():
    f = FakeRethinkDB()
    try:
        t = {"reql-conn-fn": lambda n: ReqlConn("127.0.0.1", f.port),
             "nodes": ["n1"]}
        c = rethinkdb.DocumentCASClient().open(t, "n1")
        c.setup(t)
        assert c.invoke(t, {"type": "invoke", "f": "write",
                            "value": ktuple(0, 3),
                            "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(0, (9, 1)), "process": 0})
        assert r["type"] == "fail"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(0, (3, 1)), "process": 0})
        assert r["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read",
                         "value": ktuple(0, None), "process": 0})
        assert r["type"] == "ok" and r["value"][1] == 1
        c.close(t)
    finally:
        f.stop()


def test_rethinkdb_hermetic_run(tmp_path):
    f = FakeRethinkDB()
    try:
        t = rethinkdb.rethinkdb_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "rate": 200, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["reql-conn-fn"] = lambda n: ReqlConn("127.0.0.1", f.port)
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_rethinkdb_db_commands():
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"]}
    db = rethinkdb.db(faketime=True)
    _with_n1(remote, lambda: (db.setup(test, "n1"),
                              db.teardown(test, "n1")))
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "apt-key add" in cmds
    assert "service rethinkdb start" in cmds
    assert "mv /usr/bin/rethinkdb /usr/bin/rethinkdb.no-faketime" \
        in cmds
    stdins = " ".join(a.get("in", "") for _h, _c, a in log
                      if isinstance(a.get("in"), str))
    assert "join=n1:29015" in stdins and "join=n3:29015" in stdins
    assert "faketime -m -f" in stdins


def test_robustirc_hermetic_run_catches_lost_messages(tmp_path):
    """A network that drops an acknowledged TOPIC must flip the set
    checker — proves the e2e wiring detects loss, not just success."""
    f = FakeRobustIRC()
    try:
        dropped = {"n": 0}

        class LossyLog(list):
            def append(self, m):
                # silently drop the third acknowledged TOPIC
                if "TOPIC" in m.get("Data", ""):
                    dropped["n"] += 1
                    if dropped["n"] == 3:
                        return
                super().append(m)

        f.messages = LossyLog(f.messages)

        t = robustirc.robustirc_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["irc-url-fn"] = lambda n: f"http://127.0.0.1:{f.port}"
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert dropped["n"] >= 3, "history must reach the dropped op"
        w = done["results"]["workload"]
        assert w["valid?"] is False and w["lost-count"] >= 1, w
    finally:
        f.stop()


def test_logcabin_hermetic_run_catches_stale_reads(tmp_path):
    """A register that answers reads from a stale snapshot (the first
    value ever written, forever) must be flagged nonlinearizable end
    to end. Note nil reads are *unconstrained* (knossos parity), so
    the stale value must be concrete."""
    sim = _LogCabinSim()
    stale = {}

    class _StaleSim:
        def __call__(self, context, action):
            cmd = action.get("cmd", "")
            r = sim(context, action)
            if " read /jepsen" in cmd:
                # pin reads to the first written value forever
                if "value" not in stale and sim.value != "null":
                    stale["value"] = sim.value
                return {"exit": 0,
                        "out": stale.get("value", "null")}
            return r

    remote = dummy.remote(responses={r"TreeOps": _StaleSim()})
    t = logcabin.logcabin_test({
        "nodes": ["n1", "n2", "n3"], "concurrency": 3,
        "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
        "faults": ["none"]})
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["remote"] = remote
    t["store-dir"] = str(tmp_path / "store")
    done = core.run(t)
    writes = sum(1 for o in done["history"]
                 if o.get("f") == "write" and o.get("type") == "ok")
    reads = sum(1 for o in done["history"]
                if o.get("f") == "read" and o.get("type") == "ok")
    assert writes and reads
    assert done["results"]["workload"]["valid?"] is False


def test_mysql_cluster_hermetic_run_catches_phantom_reads(tmp_path):
    """An engine that answers one register read with a value nobody
    ever wrote (writes draw from 0..4) must be flagged
    nonlinearizable end to end."""
    import sql_engine

    class _CorruptingEngine(sql_engine.Engine):
        def __init__(self):
            super().__init__()
            self.reads = 0

        def session(self):
            s = super().session()
            eng = self
            orig = s.execute

            def execute(sql):
                rows, cols = orig(sql)
                if sql.lower().startswith(
                        "select val from registers"):
                    eng.reads += 1
                    if eng.reads == 5:
                        return [(7,)], cols
                return rows, cols

            s.execute = execute
            return s

    eng = _CorruptingEngine()
    f = FakeMySQLServer(engine=eng)
    try:
        t = mysql_cluster.mysql_cluster_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["sql-conn-fn"] = lambda n: MyConn("127.0.0.1", f.port)
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert eng.reads >= 5, "history must reach the corrupted read"
        assert done["results"]["workload"]["valid?"] is False
    finally:
        f.stop()


def test_rethinkdb_hermetic_run_catches_phantom_reads(tmp_path):
    """A fake that serves one document-cas read with a never-written
    value must flip the per-key linearizability checker end to end."""
    from jepsen_tpu.suites import reql_proto as rq

    f = FakeRethinkDB()
    corrupted = {"n": 0}

    def corrupt(term, out):
        # reads are `default(get_field(get(tbl, k), 'val'), None)`;
        # corrupt the third concrete read (nil reads are
        # unconstrained, so the lie must be a real value)
        if (isinstance(term, list) and term[0] == rq.T_DEFAULT
                and out is not None):
            corrupted["n"] += 1
            if corrupted["n"] == 3:
                return 999
        return out

    f.corrupt_hook = corrupt
    try:
        t = rethinkdb.rethinkdb_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "rate": 200, "time-limit": 3,
            "faults": ["none"]})
        t["db"] = jepsen_tpu.db.noop
        t["os"] = jepsen_tpu.os_.noop
        t["reql-conn-fn"] = lambda n: ReqlConn("127.0.0.1", f.port)
        t["store-dir"] = str(tmp_path / "store")
        done = core.run(t)
        assert corrupted["n"] >= 3, "history must reach the lie"
        assert done["results"]["workload"]["valid?"] is False
    finally:
        f.stop()
