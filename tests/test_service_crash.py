"""Crash consistency for the verification service (ISSUE 17): durable
periodic checkpoints, `recover()` after an ungraceful death, epoch
fencing, corrupt-manifest tolerance, and standby failover.

The chaos pin: SIGKILL a daemon process at a random point mid-stream
(after at least one durable checkpoint landed) across both kernel
families, `recover()` in a fresh service, and the resumed verdicts /
frontiers / blame / attested counts are byte-identical-as-canonical-
JSON to an uninterrupted solo run — no drain manifest required.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from jepsen_tpu import models, service, store
from jepsen_tpu.checker import streaming, synth

MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8
FRONTIER = 128
CKPT = 2
TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    from jepsen_tpu import _platform
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


def _canon(x):
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def _strip(d, extra=()):
    return _canon({k: v for k, v in d.items()
                   if k not in TIMING + tuple(extra)})


def _jops(h):
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


def _solo(ops, **kw):
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT,
                            **kw)
    for op in ops:
        s.feed(op)
    return s.finish()


def _wgl_spec(**over):
    sp = {"kind": "wgl", "model": service.model_spec(MODEL),
          "chunk-entries": CHUNK, "slots": SLOTS, "engine": "sort",
          "frontier": FRONTIER, "checkpoint-every": CKPT}
    sp.update(over)
    return sp


def _write_journal(run_dir, ops):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "journal.jsonl"), "w") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default)
                     + "\n")


def _write_history_gz(run_dir, ops):
    import gzip
    with gzip.open(os.path.join(run_dir, "history.jsonl.gz"),
                   "wt") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default)
                     + "\n")


def _wait(pred, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def _wait_results(run_dir, timeout_s=120.0):
    path = os.path.join(run_dir, store.STREAMED_RESULTS_FILE)
    assert _wait(lambda: os.path.exists(path), timeout_s), \
        f"no streamed results in {run_dir}"
    # the writer is not atomic with the watcher's seal: retry briefly
    deadline = time.monotonic() + 5.0
    while True:
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


# -- the chaos pin: SIGKILL mid-stream, recover(), identical verdicts -------

# the child daemon: admits every journal under the store via spec_fn
# and tails them. It NEVER seals (the parent withholds history.jsonl.gz
# until after the kill), so it sits mid-stream with durable periodic
# checkpoints landing — the parent SIGKILLs it once both streams have
# persisted a carry checkpoint.
_CHILD = textwrap.dedent("""
    import json, sys, time
    from jepsen_tpu import service

    root = sys.argv[1]
    specs = json.load(open(sys.argv[2]))

    def spec_fn(d):
        for name, spec in specs.items():
            if name in d:
                return spec
        return None

    svc = service.VerificationService()
    svc.recover(root, spec_fn=spec_fn)
    svc.watch(root, spec_fn=spec_fn)
    print("READY", flush=True)
    while True:
        time.sleep(0.1)
""")


@pytest.mark.slow
def test_sigkill_recover_smoke(tmp_path):
    """SIGKILL a daemon subprocess mid-stream across both kernel
    families; recover() in a fresh service resumes from the durable
    checkpoints and the verdicts are byte-identical to solo runs."""
    root = str(tmp_path / "st")
    n = 400
    # seeds chosen so the solo runs hit no mid-stream encoder rebuild:
    # a rebuild's replay re-dispatches chunks, and how many depends on
    # how far the pump got — which would make attested tallies differ
    # between pump schedules rather than between crash and no-crash
    fams = {
        "sortfam": (42, _wgl_spec(), {}),
        "densefam": (43, _wgl_spec(engine="dense",
                                   **{"state-range": [0, 5]}),
                     {"engine": "dense", "state_range": (0, 5)}),
    }
    ops_by, solo_by, dirs = {}, {}, {}
    for fam, (seed, spec, solo_kw) in fams.items():
        h = synth.register_history(n, concurrency=3, values=5,
                                   seed=seed)
        ops = _jops(h)
        ops_by[fam] = ops
        solo_by[fam] = _solo(ops, **solo_kw)
        d = os.path.join(root, fam, "0")
        dirs[fam] = d
        _write_journal(d, ops)

    spec_path = str(tmp_path / "specs.json")
    with open(spec_path, "w") as fh:
        json.dump({fam: {"linear": spec}
                   for fam, (_seed, spec, _s) in fams.items()}, fh)
    child_path = str(tmp_path / "child.py")
    with open(child_path, "w") as fh:
        fh.write(_CHILD)

    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(service.__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH"))
                   if p))
    proc = subprocess.Popen([sys.executable, child_path, root,
                             spec_path], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL)
    try:
        def checkpointed(d):
            man = store.load_service_resume(d)
            if not man:
                return False
            cks = man.get("checkpoints") or {}
            return any("carry" in ck for ck in cks.values())

        # SIGKILL lands mid-stream: after the first durable carry
        # checkpoint of each family, with the streams still live
        assert _wait(lambda: all(checkpointed(d)
                                 for d in dirs.values()),
                     timeout_s=180.0), \
            "daemon never persisted a periodic checkpoint"
    finally:
        proc.kill()         # SIGKILL: no drain, no manifest flush
        proc.wait(30)

    # no verdicts were delivered; the manifests are the only trace
    for d in dirs.values():
        assert not os.path.exists(
            os.path.join(d, store.STREAMED_RESULTS_FILE))
        _write_history_gz(d, ops_by[os.path.basename(
            os.path.dirname(d))])

    svc = service.VerificationService()
    try:
        names = svc.recover(root)
        assert sorted(names) == sorted(f"{f}/0" for f in fams), names
        assert svc.recovered_total == 2
        assert svc.epoch == 2   # the dead daemon held epoch 1
        for fam in fams:
            got = _wait_results(dirs[fam])
            assert _strip(got["linear"]) == _strip(solo_by[fam]), fam
    finally:
        svc.stop()


# -- durable periodic checkpoints (in-process) ------------------------------

def test_periodic_checkpoints_persist_without_drain(tmp_path):
    """Every checkpoint_every cycle the worker persists the exported
    carry + journal offset + attestation tallies atomically — and the
    manifest is cleared once the verdict lands."""
    ops, solo = _hist_cached(52)
    run_dir = str(tmp_path / "t" / "0")
    os.makedirs(run_dir)
    svc = service.VerificationService()
    w = svc.admit("t/0", {"linear": _wgl_spec()}, store_dir=run_dir)
    for op in ops:
        assert w.offer(op, 5.0)
    # a durable manifest appears while the stream is mid-flight —
    # no drain, no seal
    assert _wait(lambda: (store.load_service_resume(run_dir)
                          or {}).get("checkpoints"), 60.0)
    man = store.load_service_resume(run_dir)
    ck = man["checkpoints"]["linear"]
    assert "carry" in ck and ck["chunks"] >= 1
    assert man["stream"] == "t/0"
    assert man["epoch"] == 0            # never claimed a store
    assert isinstance(man["journal-offset"], int)
    w.seal()
    assert w.done.wait(60.0)
    assert _strip(w.results["linear"]) == _strip(solo)
    # verdict delivered: the resume manifest is gone
    assert store.load_service_resume(run_dir) is None
    svc.stop()


_HISTS: dict = {}


def _hist_cached(seed, n=300):
    if seed not in _HISTS:
        h = synth.register_history(n, concurrency=3, values=5,
                                   seed=seed)
        ops = _jops(h)
        _HISTS[seed] = (ops, _solo(ops))
    return _HISTS[seed]


# -- corrupt / truncated manifest tolerance (satellite bugfix) --------------

def test_corrupt_resume_manifest_starts_cold(tmp_path):
    """A corrupt resume.json must not crash the daemon: recover()
    logs a warning and re-checks the run cold from its journal."""
    ops, solo = _hist_cached(52)
    root = str(tmp_path / "st")
    d = os.path.join(root, "t", "0")
    _write_journal(d, ops)
    _write_history_gz(d, ops)
    svcdir = os.path.join(d, "service")
    os.makedirs(svcdir)
    with open(os.path.join(svcdir, "resume.json"), "w") as fh:
        fh.write('{"stream": "t/0", "targets": {"linear"')  # truncated
    assert store.load_service_resume(d) is None

    svc = service.VerificationService()
    try:
        names = svc.recover(root,
                            spec_fn=lambda _d: {"linear": _wgl_spec()})
        assert names == ["t/0"]
        got = _wait_results(d)
        assert _strip(got["linear"]) == _strip(solo)
    finally:
        svc.stop()


def test_truncated_checkpoint_npz_resumes_cold(tmp_path):
    """A manifest whose carry .npz is truncated resumes that target
    cold (journal re-check) instead of crashing — and still reaches
    the same verdict."""
    ops, solo = _hist_cached(55)
    root = str(tmp_path / "st")
    d = os.path.join(root, "t", "0")
    _write_journal(d, ops)
    _write_history_gz(d, ops)

    # a real manifest from a real half-fed stream, then truncate
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT)
    for op in ops[:200]:
        s.feed(op)
    s.checkpoint_now()
    ck = s.export_checkpoint()
    assert ck is not None and "carry" in ck
    store.write_service_resume(d, {
        "stream": "t/0", "targets": {"linear": _wgl_spec()},
        "ops-fed": 200, "checkpoints": {"linear": ck}})
    svcdir = os.path.join(d, "service")
    npz = [fn for fn in os.listdir(svcdir) if fn.endswith(".npz")]
    assert npz
    for fn in npz:
        p = os.path.join(svcdir, fn)
        with open(p, "rb") as fh:
            blob = fh.read()
        with open(p, "wb") as fh:
            fh.write(blob[: len(blob) // 2])

    man = store.load_service_resume(d)
    assert man is not None
    assert "linear" not in (man.get("checkpoints") or {})

    svc = service.VerificationService()
    try:
        names = svc.recover(root)
        assert names == ["t/0"]
        got = _wait_results(d)
        assert _strip(got["linear"]) == _strip(solo)
    finally:
        svc.stop()


# -- epoch fencing ----------------------------------------------------------

def test_epoch_fencing(tmp_path):
    """A second claimant bumps the store epoch; the first instance
    notices at its next durable write, stops persisting, and refuses
    new admissions — the new owner's state wins."""
    root = str(tmp_path / "st")
    os.makedirs(root)
    a = service.VerificationService()
    b = service.VerificationService()
    assert store.service_epoch(root) == 0
    assert a.claim_store(root) == 1
    assert not a.fenced()
    assert b.claim_store(root) == 2
    assert not b.fenced()
    assert a.fenced()               # sticky from here on
    assert a.fenced()
    with pytest.raises(service.AdmissionRefused):
        a.admit("x", {"linear": _wgl_spec()})
    b.admit("x", {"linear": _wgl_spec()})   # the new owner admits
    b.stop()
    a.stop()


def test_fenced_worker_stops_persisting(tmp_path):
    """A fenced-out service's workers must not clobber the new
    owner's manifests or verdicts."""
    ops, _solo_r = _hist_cached(52)
    root = str(tmp_path / "st")
    d = os.path.join(root, "t", "0")
    os.makedirs(d)
    a = service.VerificationService()
    a.claim_store(root)
    w = a.admit("t/0", {"linear": _wgl_spec()}, store_dir=d)
    for op in ops[:100]:
        w.offer(op, 5.0)
    assert _wait(lambda: store.load_service_resume(d) is not None,
                 60.0)
    # another instance claims the store: a's next persist is dropped
    b = service.VerificationService()
    b.claim_store(root)
    store.clear_service_resume(d)   # b's world: no manifest
    for op in ops[100:]:
        w.offer(op, 5.0)
    w.seal()
    assert w.done.wait(60.0)
    assert a.fenced()
    assert store.load_service_resume(d) is None
    assert not os.path.exists(
        os.path.join(d, store.STREAMED_RESULTS_FILE))
    a.stop()
    b.stop()


# -- standby failover -------------------------------------------------------

def test_standby_promotes_and_serves_correct_verdict(tmp_path):
    """End to end: a client streams through the primary (with durable
    checkpoints landing); the primary dies; the standby's health
    probes fail, it fences the primary, recovers the stream from its
    checkpoints, and serves — and the client fails over its address
    list, learns the stream is journal-fed, and the promoted standby
    delivers a verdict identical to a solo run."""
    ops, solo = _hist_cached(56)
    root = str(tmp_path / "st")
    d = os.path.join(root, "t", "0")
    _write_journal(d, ops)      # core.run's write-ahead journal
    addr_a = str(tmp_path / "a.sock")
    addr_b = str(tmp_path / "b.sock")

    primary = service.VerificationService()
    primary.claim_store(root)
    assert primary.serve(addr_a) == addr_a

    test = {"name": "t", "start-time": "0",
            "store-dir": root}      # dir_name -> root/t/0
    c = service.ServiceClient(f"{addr_a},{addr_b}", test,
                              spec={"linear": _wgl_spec()})
    assert c._store_dir == os.path.abspath(d)
    for op in ops[:450]:
        c.offer(op)
    assert _wait(lambda: (store.load_service_resume(d)
                          or {}).get("checkpoints"), 60.0)

    # the primary "dies": acceptor closed AND the established
    # connection cut (what a dead host's RST would do). shutdown, not
    # close: the reader thread's makefile holds an io-ref, so close()
    # defers the real close and sends would still succeed
    primary.stop()
    import socket as _socket
    try:
        c._wrap.conn().sock.shutdown(_socket.SHUT_RDWR)
    except OSError:
        pass

    standby = service.VerificationService()
    sb = service.Standby(standby, addr_a, root, bind=addr_b,
                         poll_s=0.05, failures=2)
    import threading
    t = threading.Thread(target=sb.run, daemon=True)
    t.start()
    try:
        assert sb.promoted.wait(120.0), "standby never promoted"
        assert sb.bound == addr_b
        assert standby.epoch > primary.epoch
        assert standby.recovered_total == 1

        # the client's next op rides the reconnect: it fails over to
        # the standby and learns the stream is journal-fed there
        c.offer(ops[450])
        assert _wait(lambda: c._journal_fed, 30.0)
        assert c.failovers >= 1
        assert not c._dead

        # the run completes: journal already has every op; saving the
        # history seals the tail and the standby delivers the verdict
        _write_history_gz(d, ops)
        got = _wait_results(d)
        assert _strip(got["linear"]) == _strip(solo)
        assert c.finalize() == {}   # analyze reuses streamed results
        assert primary.fenced()
    finally:
        sb.stop()
        standby.stop()
        c.close()
