"""Framework-wide sweep for the one-shot-generator starvation class.

A bare op dict in a `gen.mix` is one-shot: once drawn, it is exhausted,
so a workload menu built from bare dicts caps the run at ~#dicts ops and
can leave an op class with a single lone invocation (the stats checker's
zero-ok starvation signature — fixed by hand for yugabyte/faunadb in
round 4: cc092e9, 5442f2a).  The reference never has this problem
because its fn generators recur for the whole run
(`jepsen/src/jepsen/generator.clj:545-590`).

This sweep guards the whole catalog.  For every suite workload menu it
builds the real test map twice — once with a short time limit, once 3x
longer — and runs each generator through the deterministic simulator on
virtual time:

  * op volume must scale with the time limit (a one-shot mix plateaus
    at ~#dicts ops regardless of the limit — the ~52-op cap the round-4
    fix names);
  * every op class must recur (>1 invocation) — unless its single op
    sits in the history's tail, where deliberate once-per-run final
    reads land (the lone-op starvation signature strikes mid-run).
"""

from __future__ import annotations

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.generator import simulate
from jepsen_tpu.suites import SUITES, suite as load_suite

RATE = 50.0
SHORT_S = 10
LONG_S = 30
MAX_OPS = 50_000  # safety bound; a healthy run lands well under this

# Workloads whose generator is a state machine advanced by live
# client/nemesis side effects — a pure simulation cannot drive them
# (the quick executor never runs invoke(), so the state that gates the
# next op never changes).  Each is exercised end-to-end by its own
# suite test instead (e.g. tests/test_suite_aerospike.py runs pause
# through the real interpreter).
LIVE_FEEDBACK = {
    ("aerospike", "pause"),
}


def _cases():
    cases = []
    for name in SUITES:
        mod = load_suite(name)
        workloads = getattr(mod, "WORKLOADS", None)
        if workloads:
            cases.extend((name, w) for w in sorted(workloads))
        else:
            cases.append((name, None))  # single-workload suite
    return cases


def _build(mod, suite_name, workload, time_limit):
    opts = {
        "ssh": {"dummy": True},
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "rate": RATE,
        "time-limit": time_limit,
        "faults": ["none"],
        # highly divisible so independent.concurrent_generator accepts
        # any of the suites' shard counts (2,3,4,5,6,10,12,15,20,30)
        "concurrency": 60,
        # chronos submits a job every job-interval seconds (30 by
        # default, matching its reference); shrink it so the job stream
        # recurs inside the sweep's short virtual windows
        "job-interval": 2.0,
    }
    if workload is not None:
        opts["workload"] = workload
    fn = getattr(mod, f"{suite_name}_test", None) or mod.zk_test
    return fn(opts)


def _client_invokes(test):
    """-> (total client invocations, {f: [positions]})."""
    ctx = gen.context({"concurrency": test.get("concurrency", 60)})
    history = simulate.quick_ops(ctx, test["generator"], test=test,
                                 max_ops=MAX_OPS)
    assert len(history) < MAX_OPS, (
        "simulation hit the op cap — generator emits unboundedly at a "
        "frozen virtual time (needs live feedback? add to "
        "LIVE_FEEDBACK)")
    positions: dict = {}
    total = 0
    for op in history:
        if op.get("type") != "invoke" or op.get("process") == gen.NEMESIS:
            continue
        positions.setdefault(op.get("f"), []).append(total)
        total += 1
    return total, positions


@pytest.mark.parametrize("suite_name,workload", _cases())
def test_no_op_class_starves(suite_name, workload):
    if (suite_name, workload) in LIVE_FEEDBACK:
        pytest.skip("generator needs live client/nemesis feedback; "
                    "covered by the suite's own interpreter-driven test")
    mod = load_suite(suite_name)

    short_total, _ = _client_invokes(
        _build(mod, suite_name, workload, SHORT_S))
    long_total, long_pos = _client_invokes(
        _build(mod, suite_name, workload, LONG_S))

    assert long_pos, f"{suite_name}/{workload}: no client ops at all"

    # a class invoked exactly once is the lone-op starvation signature
    # — unless its one op sits in the history's tail, where deliberate
    # once-per-run final reads land
    tail_start = long_total - max(1, long_total // 10)
    starved = sorted(
        str(f) for f, pos in long_pos.items()
        if len(pos) == 1 and pos[0] < tail_start)
    counts = {f: len(p) for f, p in long_pos.items()}
    assert not starved, (
        f"{suite_name}/{workload}: op classes {starved} invoked only "
        f"once, mid-run — one-shot generator starvation "
        f"(counts: {counts})")

    # a recurring generator's op volume grows ~linearly with the time
    # limit; a one-shot mix plateaus at the same count for both runs
    assert long_total >= 1.8 * short_total, (
        f"{suite_name}/{workload}: {short_total} ops at {SHORT_S}s but "
        f"only {long_total} at {LONG_S}s — generator exhausts instead "
        f"of recurring")
