"""End-to-end tests for bench.py's orchestration: the degraded
host-only mode and the one-parseable-JSON-line contract.

These run the real orchestrator as a subprocess at tiny scales
(BENCH_N_OPS/BENCH_N_TXNS), so they cover exactly the code the driver
executes at round end — including the failure path that cost round 4
its TPU evidence (a wedged backend must yield a diagnosable JSON line
with host numbers attached, never a stack trace or a hang)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.perf  # ~3 min of subprocess pipelines

BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")

FAST_ENV = {
    "BENCH_N_OPS": "300",
    "BENCH_N_TXNS": "2000",
    "BENCH_HOST_BUDGET_S": "2",
    "BENCH_PREFLIGHT_ATTEMPTS": "1",
    "BENCH_PREFLIGHT_TIMEOUT_S": "30",
}


def _run_bench(extra_env: dict, timeout: int = 420):
    env = {**os.environ, **FAST_ENV, **extra_env}
    p = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env)
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no stdout at all; stderr: {p.stderr[-500:]}"
    # the contract: exactly one line, and it parses
    assert len(lines) == 1, f"expected one JSON line, got {lines}"
    return p.returncode, json.loads(lines[0])


def test_degraded_mode_reports_host_numbers():
    # an unknown platform makes the preflight probe fail fast and
    # deterministically — the orchestrator must degrade, not crash
    rc, out = _run_bench({"JAX_PLATFORMS": "no-such-platform"})
    # a missing backend exits 0: the host-only JSON line IS the round's
    # result (rc 1 made drivers discard it — BENCH_r05's rc:1 +
    # parsed:null); the "error" field still marks the WGL numbers absent
    assert rc == 0
    assert out["error"] == "tpu-backend-unavailable"
    assert out["value"] is None
    assert "preflight" in out["extra"] and "backend" not in out["extra"]
    # host-capable sections still produced numbers
    cfg = out["extra"]["configs"]
    assert cfg["3_elle_wr_10k"]["txns_per_s"] > 0
    c5 = cfg["5_elle_append_100k"]
    assert c5["txns_per_s"] > 0
    assert c5["injected_cycle_classify"].startswith("host")
    assert out["extra"]["generator_ops_per_s"] > 0
    # the committed hardware evidence rides along, clearly provenanced
    lkg = out["extra"]["last_known_good_tpu_run"]
    assert lkg["value"] > 0 and lkg["source"].startswith("doc/perf/")
    assert "NOT" in lkg["note"]
    # device-only sections were skipped, not errored
    assert out["extra"]["sections"]["headline"] == {
        "skipped": "backend unavailable"}
    assert out["extra"]["sections"]["config4"] == {
        "skipped": "backend unavailable"}
    # non-default scales must be stamped so this artifact can never
    # pass for a real 10k/100k run
    assert out["extra"]["scale_override"] == {"n_ops": 300,
                                              "n_txns": 2000}


def test_total_budget_exhaustion_soft_fails_with_final_json():
    """One hung/slow config must never turn the round into rc=1 with
    no output (the r05 failure mode): sections past the whole-run soft
    budget are marked {"ok": false, "timeout": true}, the final JSON
    line still lands, and an over-budget-only round exits 0."""
    rc, out = _run_bench({"JAX_PLATFORMS": "cpu",
                          "BENCH_TOTAL_BUDGET_S": "1"})
    assert rc == 0
    assert out["error"].startswith("sections-over-budget:")
    sections = out["extra"]["sections"]
    # every section accounted for (the orchestrator table), every one
    # soft-failed rather than silently dropped
    import bench
    assert len(sections) == len(bench.SECTIONS)
    for name, meta in sections.items():
        assert meta == {"ok": False, "timeout": True,
                        "skipped": "total bench budget exhausted"}, \
            (name, meta)
    assert out["value"] is None


def test_healthy_cpu_run_full_pipeline():
    # CPU platform: every section runs; value/vs_baseline are real
    rc, out = _run_bench({"JAX_PLATFORMS": "cpu"}, timeout=900)
    assert rc == 0, out.get("error")
    assert out["value"] and out["value"] > 0
    assert out["vs_baseline"] > 0
    cfg = out["extra"]["configs"]
    for key in ("1_register_200", "2_register_wgl_2k", "3_elle_wr_10k",
                "4_sharded_50k", "5_elle_append_100k"):
        assert key in cfg, f"missing section result {key}"
    adv = out["extra"]["adversarial_10k"]
    assert adv["tpu"]["verdict"] == "True"
    assert out["extra"]["backend"]["platform"] == "cpu"
