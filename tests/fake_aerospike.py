"""In-process fake Aerospike server speaking the wire subset in
`jepsen_tpu/suites/as_proto.py`: message protocol (get / put with
generation and create-only policies / append / incr) and the text info
protocol. Single consistent store — the fake is a *correct* server, so
valid workloads must check valid."""

from __future__ import annotations

import socket
import struct
import threading

from jepsen_tpu.suites import as_proto as p


class FakeAerospike:
    def __init__(self):
        self.store: dict[tuple, dict] = {}   # (ns,set,key) -> record
        self.lock = threading.Lock()
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(64)
        self.port = self.srv.getsockname()[1]
        self.running = True
        self._conns: list = []
        threading.Thread(target=self._accept, daemon=True).start()

    def stop(self):
        """Shut down fully: close the listener AND every accepted
        session socket, so in-flight clients see the server die
        (tests rely on this to exercise error classification)."""
        self.running = False
        try:
            self.srv.close()
        except OSError:
            pass
        with self.lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def _accept(self):
        while self.running:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            # request/response protocol: Nagle + delayed ACK cost
            # ~40ms per round trip without this
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self.lock:
                if not self.running:
                    conn.close()
                    continue
                self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _read_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn):
        try:
            while True:
                proto, = struct.unpack(">Q", self._read_exact(conn, 8))
                size = proto & ((1 << 48) - 1)
                ptype = (proto >> 48) & 0xFF
                payload = self._read_exact(conn, size)
                if ptype == p.T_INFO:
                    reply = self._info(payload)
                    hdr = struct.pack(
                        ">Q", (2 << 56) | (p.T_INFO << 48) | len(reply))
                    conn.sendall(hdr + reply)
                else:
                    conn.sendall(self._message(payload))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _info(self, payload: bytes) -> bytes:
        out = []
        for cmd in payload.decode().splitlines():
            if not cmd:
                continue
            if cmd == "status":
                out.append("status\tok")
            elif cmd.startswith("roster:"):
                out.append(f"{cmd}\troster=null:pending_roster=null:"
                           f"observed_nodes=null")
            elif cmd.startswith(("recluster", "revive")):
                out.append(f"{cmd}\tok")
            else:
                out.append(f"{cmd}\tunknown")
        return ("\n".join(out) + "\n").encode()

    def _message(self, payload: bytes) -> bytes:
        rc, gen_in, fields, (i1, i2, i3), bins_in = \
            p.decode_message(payload)
        fmap = dict(fields)
        ns = fmap.get(p.FIELD_NAMESPACE, b"").decode()
        st = fmap.get(p.FIELD_SET, b"").decode()
        kb = fmap.get(p.FIELD_KEY, b"\x01")
        key = p._decode_value(kb[0], kb[1:])
        k = (ns, st, key)

        def reply(code, generation=0, bins=None):
            ops = [p._op(p.OP_READ, name, v)
                   for name, v in (bins or {}).items()]
            return p.encode_message(0, 0, 0, generation, [], ops,
                                    result_code=code)

        with self.lock:
            rec = self.store.get(k)
            if i1 & p.INFO1_READ:
                if rec is None:
                    return reply(p.RC_KEY_NOT_FOUND)
                return reply(p.RC_OK, rec["generation"],
                             dict(rec["bins"]))
            if i2 & p.INFO2_WRITE:
                if i2 & p.INFO2_CREATE_ONLY and rec is not None:
                    return reply(p.RC_KEY_EXISTS)
                if i2 & p.INFO2_GENERATION and \
                        (rec is None or rec["generation"] != gen_in):
                    return reply(p.RC_GENERATION)
                if rec is None:
                    rec = {"generation": 0, "bins": {}}
                    self.store[k] = rec
                # bins_in values decoded by decode_message; op types are
                # lost there, so the client re-encodes intent via the
                # per-op type byte — recover it from the raw payload
                for op_type, name, value in _ops(payload):
                    if op_type == p.OP_WRITE:
                        rec["bins"][name] = value
                    elif op_type == p.OP_APPEND:
                        cur = rec["bins"].get(name, "")
                        if not isinstance(cur, str) \
                                or not isinstance(value, str):
                            return reply(p.RC_PARAMETER)
                        rec["bins"][name] = cur + value
                    elif op_type == p.OP_INCR:
                        cur = rec["bins"].get(name, 0)
                        if not isinstance(cur, int) \
                                or not isinstance(value, int):
                            return reply(p.RC_PARAMETER)
                        rec["bins"][name] = cur + value
                    else:
                        return reply(p.RC_PARAMETER)
                rec["generation"] += 1
                return reply(p.RC_OK, rec["generation"])
        return reply(p.RC_PARAMETER)


def _ops(payload: bytes):
    """Yield (op_type, bin_name, value) from a raw message payload."""
    (hsz, _i1, _i2, _i3, _u, _rc, _gen, _exp, _ttl,
     n_fields, n_ops) = struct.unpack(">BBBBBBIIIHH", payload[:22])
    off = hsz
    for _ in range(n_fields):
        sz, = struct.unpack(">I", payload[off:off + 4])
        off += 4 + sz
    for _ in range(n_ops):
        sz, = struct.unpack(">I", payload[off:off + 4])
        op_type, pt, _ver, nlen = struct.unpack(
            ">BBBB", payload[off + 4:off + 8])
        name = payload[off + 8:off + 8 + nlen].decode()
        vdata = payload[off + 8 + nlen:off + 4 + sz]
        yield op_type, name, p._decode_value(pt, vdata)
        off += 4 + sz
