"""Hazelcast-style CP-menu suite tests: the shim's primitives, each
workload client, the suite-local semaphore checker, and hermetic runs
of every menu entry against the in-process shim."""

import pytest

from jepsen_tpu import core
from jepsen_tpu.suites import cp_shim, hazelcast


@pytest.fixture
def shim():
    server, port = cp_shim.serve()
    yield server, port
    server.shutdown()


def url_fn(port):
    return lambda node: f"http://127.0.0.1:{port}"


def test_shim_lock_semantics(shim):
    server, port = shim
    c = hazelcast.http_post
    u = f"http://127.0.0.1:{port}"
    assert c(u + "/lock/acquire", {"name": "l", "owner": "a"})["ok"]
    assert not c(u + "/lock/acquire", {"name": "l", "owner": "b"})["ok"]
    assert not c(u + "/lock/release", {"name": "l", "owner": "b"})["ok"]
    assert c(u + "/lock/release", {"name": "l", "owner": "a"})["ok"]
    assert c(u + "/lock/acquire", {"name": "l", "owner": "b"})["ok"]


def test_shim_semaphore(shim):
    _server, port = shim
    c = hazelcast.http_post
    u = f"http://127.0.0.1:{port}"
    assert c(u + "/semaphore/acquire",
             {"name": "s", "owner": "a", "permits": 2})["ok"]
    assert c(u + "/semaphore/acquire",
             {"name": "s", "owner": "b", "permits": 2})["ok"]
    assert not c(u + "/semaphore/acquire",
                 {"name": "s", "owner": "c", "permits": 2})["ok"]
    assert c(u + "/semaphore/release", {"name": "s", "owner": "a"})["ok"]
    assert c(u + "/semaphore/acquire",
             {"name": "s", "owner": "c", "permits": 2})["ok"]


def test_shim_ids_and_queue(shim):
    _server, port = shim
    c = hazelcast.http_post
    u = f"http://127.0.0.1:{port}"
    ids = {c(u + "/id", {})["value"] for _ in range(10)}
    assert len(ids) == 10
    c(u + "/queue/offer", {"name": "q", "value": 1})
    c(u + "/queue/offer", {"name": "q", "value": 2})
    assert c(u + "/queue/poll", {"name": "q"})["value"] == 1
    assert c(u + "/queue/poll", {"name": "q"})["value"] == 2
    assert c(u + "/queue/poll", {"name": "q"})["value"] is None


def test_semaphore_checker():
    def pair(f, p):
        return [{"type": "invoke", "f": f, "process": p},
                {"type": "ok", "f": f, "process": p}]

    ok = (pair("acquire", 0) + pair("acquire", 1)
          + pair("release", 0) + pair("acquire", 2))
    assert hazelcast.SemaphoreChecker(2).check({}, ok, {})["valid?"]
    bad = pair("acquire", 0) + pair("acquire", 1) + pair("acquire", 3)
    res = hazelcast.SemaphoreChecker(2).check({}, bad, {})
    assert res["valid?"] is False
    assert res["over-capacity"]


def test_menu_names():
    assert set(hazelcast.WORKLOADS) == \
        {"lock", "semaphore", "cas-register", "unique-ids", "queue",
         "queue-linear", "map", "crdt-map", "crdt-map-linear"}


def test_server_db_commands(tmp_path):
    """The real-server install path uploads the fat jar and daemonizes
    java -jar --members (hazelcast.clj:70-96)."""
    from jepsen_tpu import control
    from jepsen_tpu.control import dummy
    jar = tmp_path / "hazelcast-server.jar"
    jar.write_bytes(b"jar")
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"], "server-jar": str(jar)}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            hazelcast.ServerDB().setup(test, "n1")
            hazelcast.ServerDB().teardown(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "/usr/bin/java" in cmds and "-jar" in cmds
    assert "--members n1,n2,n3" in cmds
    assert "/opt/hazelcast/server.jar" in str(remote.files) \
        or "server.jar" in cmds


@pytest.mark.parametrize("workload", sorted(hazelcast.WORKLOADS))
def test_hermetic_menu_run(tmp_path, shim, workload):
    import jepsen_tpu.db
    import jepsen_tpu.nemesis
    import jepsen_tpu.os_
    _server, port = shim
    t = hazelcast.hazelcast_test({
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 3,
        "ssh": {"dummy": True},
        "workload": workload,
        "rate": 100,
        "time-limit": 2,
        "nemesis": "none",
        "store-dir": str(tmp_path / "store"),
    })
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["shim-url-fn"] = url_fn(port)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, {k: v.get("valid?")
                                   for k, v in res.items()
                                   if isinstance(v, dict)}
    assert len(done["history"]) > 10


def test_semaphore_checker_tolerates_release_completion_reordering():
    """A release takes effect between its invoke and its ok: an
    acquire granted against the freed permit may journal its ok BEFORE
    the release's ok. That interleaving is legal and must verify."""
    from jepsen_tpu.suites.hazelcast import SemaphoreChecker

    hist = [
        {"type": "invoke", "f": "acquire", "process": 0, "time": 0},
        {"type": "ok", "f": "acquire", "process": 0, "time": 1},
        {"type": "invoke", "f": "acquire", "process": 1, "time": 2},
        {"type": "ok", "f": "acquire", "process": 1, "time": 3},
        # p0 releases; the server frees the permit and grants p2's
        # acquire, whose completion lands in the journal first
        {"type": "invoke", "f": "release", "process": 0, "time": 4},
        {"type": "invoke", "f": "acquire", "process": 2, "time": 5},
        {"type": "ok", "f": "acquire", "process": 2, "time": 6},
        {"type": "ok", "f": "release", "process": 0, "time": 7},
    ]
    res = SemaphoreChecker(2).check({}, hist, {})
    assert res["valid?"] is True, res
    # a genuine third concurrent holder is still flagged
    bad = hist[:4] + [
        {"type": "invoke", "f": "acquire", "process": 2, "time": 5},
        {"type": "ok", "f": "acquire", "process": 2, "time": 6},
    ]
    res = SemaphoreChecker(2).check({}, bad, {})
    assert res["valid?"] is False and res["over-capacity"]


def test_semaphore_checker_counts_multi_permit_holders():
    """One process may hold several permits (the shim's holders list
    has one entry per acquire); a set-based checker would undercount."""
    from jepsen_tpu.suites.hazelcast import SemaphoreChecker

    def pair(f, p):
        return [{"type": "invoke", "f": f, "process": p},
                {"type": "ok", "f": f, "process": p}]

    # p0 holds both permits, then p1's grant is a genuine violation
    bad = pair("acquire", 0) + pair("acquire", 0) + pair("acquire", 1)
    res = SemaphoreChecker(2).check({}, bad, {})
    assert res["valid?"] is False and res["over-capacity"]


def test_semaphore_checker_restores_failed_release():
    """A failed release never freed its permit: an acquire granted
    during the release's flight makes three certain holders."""
    from jepsen_tpu.suites.hazelcast import SemaphoreChecker

    def pair(f, p):
        return [{"type": "invoke", "f": f, "process": p},
                {"type": "ok", "f": f, "process": p}]

    hist = (pair("acquire", 0) + pair("acquire", 1)
            + [{"type": "invoke", "f": "release", "process": 0},
               {"type": "invoke", "f": "acquire", "process": 2},
               {"type": "ok", "f": "acquire", "process": 2},
               {"type": "fail", "f": "release", "process": 0}])
    res = SemaphoreChecker(2).check({}, hist, {})
    assert res["valid?"] is False and res["over-capacity"], res
