"""An in-process Postgres-protocol server backed by sql_engine, standing
in for CockroachDB: exercises the suite's wire client
(`jepsen_tpu/suites/pg_proto.py`) against real v3 framing with trust
auth, hermetic serializable data layer.
"""

from __future__ import annotations

import socketserver

from netutil import NodelayHandler
import struct
import threading

from sql_engine import Engine, SQLError


def _msg(typ: bytes, body: bytes) -> bytes:
    return typ + struct.pack("!I", len(body) + 4) + body


class _Handler(NodelayHandler):

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def _error(self, code: str, msg: str):
        body = b"SERROR\0" + b"C" + code.encode() + b"\0" + \
            b"M" + msg.encode() + b"\0\0"
        self.request.sendall(_msg(b"E", body))

    def _ready(self, session):
        status = b"T" if session.in_txn else b"I"
        self.request.sendall(_msg(b"Z", status))

    def _resultset(self, rows, cols):
        body = struct.pack("!H", len(cols))
        for c in cols:
            body += c.encode() + b"\0" + struct.pack("!IHIHIH", 0, 0, 25,
                                                     65535, 0, 0)
        out = _msg(b"T", body)
        for row in rows:
            rb = struct.pack("!H", len(row))
            for v in row:
                if v is None:
                    rb += struct.pack("!i", -1)
                else:
                    vb = str(v).encode()
                    rb += struct.pack("!i", len(vb)) + vb
            out += _msg(b"D", rb)
        out += _msg(b"C", b"SELECT %d\0" % len(rows))
        self.request.sendall(out)

    def handle(self):
        srv: "FakePGServer" = self.server  # type: ignore[assignment]
        session = srv.engine.session()
        try:
            # startup message (possibly preceded by SSLRequest)
            while True:
                n = struct.unpack("!I", self._recv_exact(4))[0] - 4
                body = self._recv_exact(n)
                if len(body) >= 4 and \
                        struct.unpack("!I", body[:4])[0] == 80877103:
                    self.request.sendall(b"N")  # no SSL
                    continue
                break
            self.request.sendall(_msg(b"R", struct.pack("!I", 0)))
            self.request.sendall(
                _msg(b"S", b"server_version\013.0-fake-cockroach\0"))
            self.request.sendall(_msg(b"K", struct.pack("!II", 1, 2)))
            self._ready(session)
            while True:
                typ = self._recv_exact(1)
                n = struct.unpack("!I", self._recv_exact(4))[0] - 4
                body = self._recv_exact(n)
                if typ == b"X":
                    return
                if typ != b"Q":
                    self._error("0A000", f"unsupported message {typ!r}")
                    self._ready(session)
                    continue
                sql = body.rstrip(b"\0").decode()
                if srv.fail_hook:
                    errc = srv.fail_hook(sql)
                    if errc:
                        self._error(*errc)
                        self._ready(session)
                        continue
                try:
                    rows, cols = session.execute(sql)
                except SQLError as e:
                    self._error(str(e.code), e.message)
                    self._ready(session)
                    continue
                if cols is None:
                    tag = b"INSERT 0 %d\0" % rows if "insert" in \
                        sql.lower()[:8] else b"OK %d\0" % rows
                    self.request.sendall(_msg(b"C", tag))
                else:
                    self._resultset(rows, cols)
                self._ready(session)
        except (ConnectionError, OSError):
            pass
        finally:
            session.abort()


class FakePGServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, engine: Engine | None = None):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.engine = engine or Engine()
        self.fail_hook = None  # fail_hook(sql) -> (sqlstate, msg) | None
        self.port = self.server_address[1]
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()
