"""In-process fakes for the elasticsearch REST subset (index docs,
MVCC versioned puts, flush, search) and the Ignite REST API
(get/put/cas/putifabs). Both consistent by construction."""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeElasticsearch:
    def __init__(self):
        self.lock = threading.Lock()
        self.docs: dict[tuple, dict] = {}  # (index, type, id) -> doc
        self.auto = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, status, obj):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):  # noqa: N802
                path = urllib.parse.urlparse(self.path).path
                parts = [p for p in path.split("/") if p]
                with outer.lock:
                    if parts[-1] == "_flush":
                        self._reply(200, {"ok": True})
                        return
                    # POST /{index}/{type}: auto-id create
                    index, dtype = parts[0], parts[1]
                    outer.auto += 1
                    outer.docs[(index, dtype, str(outer.auto))] = {
                        "_source": self._body(), "_version": 1}
                    self._reply(201, {"created": True})

            def do_GET(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                with outer.lock:
                    if parts[-1] == "_search":
                        index = parts[0]
                        hits = [{"_id": k[2], "_source": d["_source"]}
                                for k, d in outer.docs.items()
                                if k[0] == index]
                        self._reply(200, {"hits": {"hits": hits}})
                        return
                    key = (parts[0], parts[1], parts[2])
                    doc = outer.docs.get(key)
                    if doc is None:
                        self._reply(404, {"found": False})
                        return
                    self._reply(200, {"found": True,
                                      "_version": doc["_version"],
                                      "_source": doc["_source"]})

            def do_PUT(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                q = urllib.parse.parse_qs(parsed.query)
                key = (parts[0], parts[1], parts[2])
                with outer.lock:
                    doc = outer.docs.get(key)
                    if "create" in q.get("op_type", []):
                        if doc is not None:
                            self._reply(409, {"error": "exists"})
                            return
                        outer.docs[key] = {"_source": self._body(),
                                           "_version": 1}
                        self._reply(201, {"created": True})
                        return
                    if "version" in q:
                        want = int(q["version"][0])
                        if doc is None or doc["_version"] != want:
                            self._reply(409, {"error": "conflict"})
                            return
                        doc["_source"] = self._body()
                        doc["_version"] += 1
                        self._reply(200, {"ok": True})
                        return
                    if doc is None:
                        outer.docs[key] = {"_source": self._body(),
                                           "_version": 1}
                        self._reply(201, {"created": True})
                    else:
                        doc["_source"] = self._body()
                        doc["_version"] += 1
                        self._reply(200, {"ok": True})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()


class FakeIgnite:
    def __init__(self):
        self.lock = threading.Lock()
        self.caches: dict[str, dict] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                q = {k: v[0] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
                cmd = q.get("cmd")
                cache = outer.caches.setdefault(
                    q.get("cacheName", "default"), {})
                with outer.lock:
                    if cmd == "get":
                        resp = cache.get(q["key"])
                    elif cmd == "put":
                        cache[q["key"]] = q["val"]
                        resp = True
                    elif cmd == "putifabs":
                        if q["key"] in cache:
                            resp = False
                        else:
                            cache[q["key"]] = q["val"]
                            resp = True
                    elif cmd == "cas":
                        # val = new, val2 = expected old
                        if str(cache.get(q["key"])) == q.get("val2"):
                            cache[q["key"]] = q["val"]
                            resp = True
                        else:
                            resp = False
                    else:
                        body = json.dumps(
                            {"successStatus": 1,
                             "error": f"bad cmd {cmd}"}).encode()
                        self.send_response(200)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                body = json.dumps({"successStatus": 0,
                                   "response": resp}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
