"""Additional-graphs (realtime / process) cycle-search tests: the
reference folds extra precedence graphs into Elle's cycle checkers
(`tests/cycle.clj:9-16`, `tests/cycle/wr.clj:17-26`); these fixtures
port that surface — including a cycle visible only through the realtime
edge and one only through the process edge — and pin host/device
agreement on every one."""

import numpy as np
import pytest

from jepsen_tpu.checker.elle import graphs, kernels, list_append, wr
from jepsen_tpu.history import history


def _ok(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn,
             "process": process, "time": t},
            {"type": "ok", "f": "txn", "value": txn, "process": process,
             "time": t + 1}]


def _info(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn,
             "process": process, "time": t},
            {"type": "info", "f": "txn", "value": txn,
             "process": process, "time": t + 1}]


# -- graph builders ---------------------------------------------------------

def test_realtime_edges_frontier_reduction():
    # T0 completes before T1 and T2 invoke; T1 completes before T2
    # invokes. The T0 -> T2 pair is implied via T0 -> T1 -> T2, so the
    # reduced edge set must not materialize it.
    h = history(_ok(0, [["w", "x", 1]], 0)
                + _ok(1, [["w", "x", 2]], 2)
                + _ok(2, [["w", "x", 3]], 4)).index()
    txns = [o for o in h if o["type"] == "ok"]
    edges = graphs.realtime_edges(h, txns)
    assert set(edges) == {(0, 1), (1, 2)}


def test_realtime_edges_concurrent_ops_unordered():
    # overlapping ops: invoke A, invoke B, ok A, ok B — no edges
    h = history([
        {"type": "invoke", "f": "txn", "value": [], "process": 0,
         "time": 0},
        {"type": "invoke", "f": "txn", "value": [], "process": 1,
         "time": 1},
        {"type": "ok", "f": "txn", "value": [], "process": 0, "time": 2},
        {"type": "ok", "f": "txn", "value": [], "process": 1, "time": 3},
    ]).index()
    txns = [o for o in h if o["type"] == "ok"]
    assert graphs.realtime_edges(h, txns) == {}


def test_info_ops_take_only_incoming_realtime_edges():
    h = history(_ok(0, [["w", "x", 1]], 0)
                + _info(1, [["w", "x", 2]], 2)
                + _ok(2, [["w", "x", 3]], 4)).index()
    txns = ([o for o in h if o["type"] == "ok"]
            + [o for o in h if o["type"] == "info"])
    edges = graphs.realtime_edges(h, txns)
    # ok(0) precedes both later ops; the info node (index 2 in txns)
    # never completes, so nothing follows it
    assert (0, 2) in edges and (0, 1) in edges
    assert not any(i == 2 for (i, _j) in edges)


def test_process_edges_chain_and_info_break():
    h = history(_ok(0, [["w", "x", 1]], 0)
                + _ok(1, [["w", "y", 1]], 1)
                + _info(0, [["w", "x", 2]], 2)).index()
    txns = ([o for o in h if o["type"] == "ok"]
            + [o for o in h if o["type"] == "info"])
    edges = graphs.process_edges(h, txns)
    # process 0: ok -> info chain edge; the info op ends the chain
    assert edges == {(0, 2): kernels._PROC}


def test_completion_only_history_gains_no_realtime_edges():
    # completion-only journals are legal checker input; without
    # invocations nothing proves any op began after another completed,
    # so the realtime graph must stay empty (edges would fabricate
    # anomalies for genuinely concurrent ops)
    h = history([
        {"type": "ok", "f": "txn", "value": [["w", "x", 1]],
         "process": 0, "time": 0},
        {"type": "ok", "f": "txn", "value": [["r", "x", None]],
         "process": 1, "time": 1},
    ]).index()
    txns = list(h)
    assert graphs.realtime_edges(h, txns) == {}
    r = wr.check(h, additional_graphs=("realtime",))
    assert r["valid?"] is True
    # same-process completions still chain in process order (a
    # sequential process proves its own op order without invocations)
    h2 = history([
        {"type": "ok", "f": "txn", "value": [["w", "x", 1]],
         "process": 0, "time": 0},
        {"type": "ok", "f": "txn", "value": [["r", "x", None]],
         "process": 0, "time": 1},
    ]).index()
    r2 = wr.check(h2, additional_graphs=("process",))
    assert r2["valid?"] is False
    assert "G-single-process" in r2["anomaly-types"]


def test_process_chain_orders_by_completion_not_invocation():
    # an op whose invocation was lost from the journal must not jump to
    # the head of its process chain (completion order is op order for a
    # sequential process)
    h = history([
        {"type": "invoke", "f": "txn", "value": [["w", "x", 1]],
         "process": 0, "time": 0},
        {"type": "ok", "f": "txn", "value": [["w", "x", 1]],
         "process": 0, "time": 1},
        {"type": "ok", "f": "txn", "value": [["r", "x", 1]],
         "process": 0, "time": 2},
    ])
    r = wr.check(h, additional_graphs=("process",))
    assert r["valid?"] is True


def test_additional_edges_unknown_graph():
    with pytest.raises(ValueError):
        graphs.additional_edges(history([]), [], ("causal",))


def test_expand_anomalies_variants():
    out = graphs.expand_anomalies(("G0", "G-single", "G1a"),
                                  ("realtime", "process"))
    assert "G0-realtime" in out and "G0-process" in out
    assert "G-single-realtime" in out
    assert "G1a-realtime" not in out


# -- kernels: union-graph classification ------------------------------------

def test_analyze_edges_realtime_only_cycle():
    edges = {(0, 1): frozenset({"realtime"}),
             (1, 0): frozenset({"rw"})}
    r = kernels.analyze_edges(2, edges)
    assert r["G-single-realtime"]
    assert not r["G-single"] and not r["G0"] and not r["G0-realtime"]


def test_analyze_edges_process_subsumed_by_base():
    # a pure-ww cycle also closed by a process edge: base G0 explains
    # it, so no variant fires for that SCC
    edges = {(0, 1): frozenset({"ww"}),
             (1, 0): frozenset({"ww", "process"})}
    r = kernels.analyze_edges(2, edges)
    assert r["G0"] and not r["G0-process"]


def test_analyze_edges_requires_subtraction_is_per_scc():
    # SCC A: pure-ww cycle. SCC B: ww + process cycle. Both G0 and
    # G0-process must be reported — the subtraction is per-SCC, not
    # global.
    edges = {(0, 1): frozenset({"ww"}), (1, 0): frozenset({"ww"}),
             (2, 3): frozenset({"ww"}), (3, 2): frozenset({"process"})}
    r = kernels.analyze_edges(4, edges)
    assert r["G0"] and r["G0-process"]


def test_analyze_edges_realtime_level_folds_process():
    # cycle needs one process and one realtime edge: reported at the
    # realtime level (realtime subsumes process), not the process level
    edges = {(0, 1): frozenset({"process"}),
             (1, 2): frozenset({"realtime"}),
             (2, 0): frozenset({"ww"})}
    r = kernels.analyze_edges(3, edges)
    assert r["G0-realtime"] and not r["G0-process"] and not r["G0"]


def test_analyze_edges_g2_variant():
    # two rw edges, closed only through a process edge
    edges = {(0, 1): frozenset({"rw"}),
             (1, 2): frozenset({"process"}),
             (2, 0): frozenset({"rw"})}
    r = kernels.analyze_edges(3, edges)
    assert r["G2-item-process"]
    assert not r["G2-item"] and not r["G-single-process"]


# -- rw-register fixtures (`tests/cycle/wr.clj`) ----------------------------

def _wr_realtime_fixture():
    # T1 writes x=1 and completes; T2 then reads nil: the stale read
    # anti-depends on T1 (rw), and T1 realtime-precedes T2
    return history(_ok(0, [["w", "x", 1]], 0)
                   + _ok(1, [["r", "x", None]], 2))


def _wr_process_fixture():
    # same shape, same process: the precedence edge is process order
    return history(_ok(0, [["w", "x", 1]], 0)
                   + _ok(0, [["r", "x", None]], 2))


def test_wr_realtime_only_cycle():
    h = _wr_realtime_fixture()
    assert wr.check(h)["valid?"] is True
    r = wr.check(h, additional_graphs=("realtime",))
    assert r["valid?"] is False
    assert "G-single-realtime" in r["anomaly-types"]
    cert = r["anomalies"]["G-single-realtime"][0]["cycle"]
    assert cert is not None and cert[0] == cert[-1]
    # the processes differ, so the process graph alone sees nothing
    assert wr.check(h, additional_graphs=("process",))["valid?"] is True


def test_wr_process_only_cycle():
    h = _wr_process_fixture()
    assert wr.check(h)["valid?"] is True
    r = wr.check(h, additional_graphs=("process",))
    assert r["valid?"] is False
    assert "G-single-process" in r["anomaly-types"]


def test_wr_process_preferred_over_realtime():
    # with both graphs on, the weaker (process) explanation wins
    r = wr.check(_wr_process_fixture(),
                 additional_graphs=("realtime", "process"))
    assert r["valid?"] is False
    assert "G-single-process" in r["anomaly-types"]
    assert "G-single-realtime" not in r["anomaly-types"]


def test_wr_g0_realtime():
    # T1 observes x=1 then writes x=2 (so ww: writer(1) -> T1) and
    # completes before writer(1) even begins: a write-order cycle
    # closed by realtime alone
    h = history(_ok(0, [["r", "x", 1], ["w", "x", 2]], 0)
                + _ok(1, [["w", "x", 1]], 2))
    r = wr.check(h, additional_graphs=("realtime",))
    assert r["valid?"] is False
    assert "G0-realtime" in r["anomaly-types"]
    cert = r["anomalies"]["G0-realtime"][0]["cycle"]
    assert cert is not None and len(cert) == 3


def test_wr_anomaly_filter_still_applies():
    # realtime cycle present but the caller only asked for G1 —
    # G-single-realtime is not in the expanded anomaly set
    r = wr.check(_wr_realtime_fixture(), anomalies=("G1a", "G1b", "G1c"),
                 additional_graphs=("realtime",))
    assert r["valid?"] is True


# -- list-append fixtures (`tests/cycle.clj`) -------------------------------

def _append_realtime_fixture():
    return history(_ok(0, [["append", "x", 1]], 0)
                   + _ok(1, [["r", "x", []]], 2))


def test_append_realtime_only_cycle():
    h = _append_realtime_fixture()
    assert list_append.check(h)["valid?"] is True
    r = list_append.check(h, additional_graphs=("realtime",))
    assert r["valid?"] is False
    assert "G-single-realtime" in r["anomaly-types"]


def test_append_process_only_cycle():
    h = history(_ok(0, [["append", "x", 1]], 0)
                + _ok(0, [["r", "x", []]], 2))
    assert list_append.check(h)["valid?"] is True
    r = list_append.check(h, additional_graphs=("process",))
    assert r["valid?"] is False
    assert "G-single-process" in r["anomaly-types"]


def test_append_valid_history_stays_valid_with_graphs():
    h = history(_ok(0, [["append", "x", 1]], 0)
                + _ok(1, [["r", "x", [1]], ["append", "x", 2]], 2)
                + _ok(0, [["r", "x", [1, 2]]], 4))
    r = list_append.check(h, additional_graphs=("realtime", "process"))
    assert r["valid?"] is True


# -- host/device agreement --------------------------------------------------

_FIXTURES = [
    ("wr-realtime", wr.check, _wr_realtime_fixture(), ("realtime",)),
    ("wr-process", wr.check, _wr_process_fixture(), ("process",)),
    ("wr-both", wr.check, _wr_process_fixture(),
     ("realtime", "process")),
    ("append-realtime", list_append.check, _append_realtime_fixture(),
     ("realtime",)),
    ("wr-g0-rt", wr.check,
     history(_ok(0, [["r", "x", 1], ["w", "x", 2]], 0)
             + _ok(1, [["w", "x", 1]], 2)), ("realtime",)),
]


@pytest.mark.parametrize("name,fn,h,graphs_", _FIXTURES,
                         ids=[f[0] for f in _FIXTURES])
def test_host_device_engines_agree(monkeypatch, name, fn, h, graphs_):
    monkeypatch.setenv("JEPSEN_TPU_ELLE_HOST", "1")
    host = fn(h, additional_graphs=graphs_)
    monkeypatch.delenv("JEPSEN_TPU_ELLE_HOST")
    dev = fn(h, additional_graphs=graphs_)
    assert host["valid?"] == dev["valid?"]
    assert host["anomaly-types"] == dev["anomaly-types"]


def test_union_rides_the_scc_device_path():
    # a 40-txn chain with one realtime-only cycle at the end: the
    # condensation isolates a single small SCC and the stacked-level
    # batched classifier (device path on this CPU backend) flags only
    # the realtime level
    ops = []
    for i in range(40):
        ops += _ok(i % 4, [["w", f"k{i}", 1]], 2 * i)
    ops += _ok(5, [["w", "z", 1]], 100)
    ops += _ok(6, [["r", "z", None]], 102)
    r = wr.check(history(ops), additional_graphs=("realtime",))
    assert r["valid?"] is False
    assert r["anomaly-types"] == ["G-single-realtime"]


def test_analyze_edges_oversized_scc_levels():
    # max_dense=2 forces the oversized host path; the ring closes only
    # through its realtime edge, so only the realtime level fires
    edges = {(i, i + 1): frozenset({"ww"}) for i in range(4)}
    edges[(4, 0)] = frozenset({"realtime"})
    r = kernels.analyze_edges(5, edges, max_dense=2)
    assert r["oversized-sccs"] == 1
    assert r["G0-realtime"] and not r["G0"]

    edges2 = {(i, i + 1): frozenset({"ww"}) for i in range(3)}
    edges2[(3, 4)] = frozenset({"rw"})
    edges2[(4, 0)] = frozenset({"realtime"})
    r2 = kernels.analyze_edges(5, edges2, max_dense=2)
    assert r2["G-single-realtime"]
    assert not r2["G-single"] and not r2["G0-realtime"]


def test_analyze_edges_with_mesh():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("sccs",))
    edges = {(0, 1): frozenset({"realtime"}),
             (1, 0): frozenset({"rw"}),
             (2, 3): frozenset({"ww"}), (3, 2): frozenset({"ww"})}
    r = kernels.analyze_edges(4, edges, mesh=mesh)
    assert r["G-single-realtime"] and r["G0"]
    assert not r["G-single"]
