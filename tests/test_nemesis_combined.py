"""Nemesis packages + membership: node specs, package gating by DB
capabilities, composition, and the membership state machine loop.

Mirrors `jepsen/test/jepsen/nemesis/combined_test.clj` behaviors.
"""



from jepsen_tpu import db, generator as gen, net
from jepsen_tpu.control import dummy
from jepsen_tpu.nemesis import combined, membership
from jepsen_tpu.util import majority

NODES = ["n1", "n2", "n3", "n4", "n5"]


class FullDB(db.DB, db.Process, db.Pause, db.Primary):
    def __init__(self):
        self.events = []

    def start(self, test, node):
        self.events.append(("start", node))
        return "started"

    def kill(self, test, node):
        self.events.append(("kill", node))
        return "killed"

    def pause(self, test, node):
        self.events.append(("pause", node))
        return "paused"

    def resume(self, test, node):
        self.events.append(("resume", node))
        return "resumed"

    def primaries(self, test):
        return [test["nodes"][0]]


def make_test(nodes=NODES):
    r = dummy.DummyRemote()
    sessions = {n: r.connect({"host": n}) for n in nodes}
    return {"nodes": list(nodes), "sessions": sessions, "net": net.noop,
            "concurrency": 2}


class TestNodeSpecs:
    def test_one(self):
        test = make_test()
        assert len(combined.db_nodes(test, db.noop, "one")) == 1

    def test_minority_majority(self):
        test = make_test()
        n = len(NODES)
        assert len(combined.db_nodes(test, db.noop, "minority")) == \
            majority(n) - 1
        assert len(combined.db_nodes(test, db.noop, "majority")) == \
            majority(n)

    def test_all_and_explicit(self):
        test = make_test()
        assert combined.db_nodes(test, db.noop, "all") == NODES
        assert combined.db_nodes(test, db.noop, ["n2"]) == ["n2"]

    def test_nil_nonempty(self):
        test = make_test()
        for _ in range(20):
            ns = combined.db_nodes(test, db.noop, None)
            assert 1 <= len(ns) <= 5

    def test_primaries(self):
        test = make_test()
        assert combined.db_nodes(test, FullDB(), "primaries") == ["n1"]

    def test_node_specs_reflect_primary(self):
        assert "primaries" not in combined.node_specs(db.noop)
        assert "primaries" in combined.node_specs(FullDB())

    def test_minority_third(self):
        assert combined.minority_third(3) == 0
        assert combined.minority_third(5) == 1
        assert combined.minority_third(6) == 1
        assert combined.minority_third(9) == 2
        assert combined.minority_third(10) == 3


class TestDBPackage:
    def test_nemesis_routes_to_db(self):
        d = FullDB()
        test = make_test()
        pkg = combined.db_package(
            {"db": d, "faults": {"kill", "pause"}})
        n = pkg["nemesis"].setup(test)
        out = n.invoke(test, {"type": "info", "f": "kill",
                              "value": "all"})
        assert set(out["value"]) == set(NODES)
        assert all(v == "killed" for v in out["value"].values())
        assert len([e for e in d.events if e[0] == "kill"]) == 5

    def test_gated_by_faults(self):
        pkg = combined.db_package({"db": FullDB(),
                                   "faults": {"partition"}})
        assert pkg["generator"] is None
        assert pkg["final-generator"] is None

    def test_gated_by_capabilities(self):
        pkg = combined.db_package({"db": db.noop,
                                   "faults": {"kill", "pause"}})
        # noop DB has no Process/Pause: no generator modes at all
        assert pkg["generator"] is None


class TestPartitionPackage:
    def test_grudge_specs(self):
        test = make_test()
        g = combined.grudge(test, db.noop, "one")
        isolated = [n for n, v in g.items() if len(v) == 4]
        assert len(isolated) == 1
        g = combined.grudge(test, db.noop, "majority")
        sizes = sorted(len(v) for v in g.values())
        assert sizes == [2, 2, 2, 3, 3]
        g = combined.grudge(test, FullDB(), "primaries")
        assert g["n1"] == {"n2", "n3", "n4", "n5"}

    def test_partition_nemesis_lifts_specs(self):
        test = make_test()
        pn = combined.PartitionNemesis(db.noop).setup(test)
        out = pn.invoke(test, {"type": "info", "f": "start-partition",
                               "value": "one"})
        assert out["f"] == "start-partition"
        assert out["value"][0] == "isolated"
        out = pn.invoke(test, {"type": "info", "f": "stop-partition"})
        assert out["f"] == "stop-partition"
        assert out["value"] == "network-healed"


class TestComposePackages:
    def test_full_package_generates_and_routes(self):
        d = FullDB()
        test = make_test()
        pkg = combined.nemesis_package(
            {"db": d, "interval": 0.0001,
             "faults": ["partition", "kill", "pause"]})
        n = pkg["nemesis"].setup(test)
        # drive the package generator deterministically
        ctx = gen.context(test)
        fs_seen = set()
        g = pkg["generator"]
        with gen.fixed_rng(7):
            for _ in range(60):
                res = gen.op(g, test, ctx)
                if res is None:
                    break
                o, g = res
                if o is gen.PENDING:
                    ctx = ctx.with_time(ctx.time + 10_000_000)
                    continue
                o = {**o, "time": ctx.time}
                fs_seen.add(o["f"])
                out = n.invoke(test, o)
                assert out["f"] == o["f"]
                ctx = ctx.with_time(ctx.time + 10_000_000)
                g = gen.update(g, test, ctx,
                               {**out, "type": "info"})
        assert "start-partition" in fs_seen or \
            "stop-partition" in fs_seen
        assert {"kill", "pause"} & fs_seen

    def test_final_generators_sequence(self):
        pkg = combined.nemesis_package(
            {"db": FullDB(), "faults": ["partition", "kill"]})
        finals = pkg["final-generator"]
        assert finals is not None

    def test_perf_union(self):
        pkg = combined.nemesis_package(
            {"db": FullDB(),
             "faults": ["partition", "kill", "pause", "clock"]})
        names = {p[0] for p in pkg["perf"]}
        assert names == {"partition", "clock", "kill", "pause"}

    def test_f_map_lifts_package(self):
        pkg = combined.partition_package(
            {"db": db.noop, "faults": {"partition"}})
        lifted = combined.f_map(lambda f: f"db1-{f}", pkg)
        test = make_test()
        n = lifted["nemesis"].setup(test)
        out = n.invoke(test, {"type": "info",
                              "f": "db1-start-partition",
                              "value": "one"})
        assert out["f"] == "db1-start-partition"
        names = {p[0] for p in lifted["perf"]}
        assert names == {"db1-partition"}


class CounterState(membership.State):
    """A toy membership state machine: ops remove a node; resolution
    happens once a quorum of node views report it gone."""

    def __init__(self):
        self.removed = set()
        self.acked = {}
        self.node_views = {}
        self.view = None

    def node_view(self, test, node):
        return sorted(set(test["nodes"]) - self.removed)

    def merge_views(self, test):
        views = list(self.node_views.values())
        return views[0] if views else None

    def fs(self):
        return {"remove-node"}

    def op(self, test):
        candidates = sorted(set(test["nodes"]) - self.removed)
        if len(candidates) <= majority(len(test["nodes"])):
            return None
        return {"type": "info", "f": "remove-node",
                "value": candidates[-1]}

    def invoke(self, test, op):
        self.removed.add(op["value"])
        return {**op, "value": [op["value"], "removed"]}

    def resolve_op(self, test, op_pair):
        op, op2 = op_pair
        node = op["value"]
        if node in self.removed and node not in self.acked:
            self.acked[node] = True
            return self
        return None


class TestMembership:
    def test_package_gated(self):
        assert membership.package({"faults": {"partition"}}) is None

    def test_generator_and_invoke_resolve(self):
        test = make_test()
        pkg = membership.package(
            {"faults": {"membership"}, "interval": 0.0001,
             "membership": {"state": CounterState()}})
        assert pkg is not None
        n = pkg["nemesis"]
        shared = pkg["state"]
        st = shared.state
        op = st.op(test)
        assert op["f"] == "remove-node" and op["value"] == "n5"
        out = n.invoke(test, op)
        assert out["value"] == ["n5", "removed"]
        # the invoke-path resolve already acked it
        assert st.acked == {"n5": True}
        assert shared.pending == {}
        n.teardown(test)

    def test_stops_at_majority(self):
        test = make_test()
        st = CounterState()
        st.removed = {"n4", "n5"}
        assert st.op(test) is None
