"""Workload generator/checker tests on literal histories, mirroring the
reference's tests/*_test.clj suites."""

import jepsen_tpu.generator as gen
from jepsen_tpu.generator import simulate as sim
from jepsen_tpu.history import history
from jepsen_tpu.independent import KV
from jepsen_tpu.workloads import (adya, bank, causal, causal_reverse,
                                  comments, linearizable_register,
                                  long_fork, monotonic, sequential,
                                  table)


# -- bank -------------------------------------------------------------------

BANK_TEST = {"accounts": [0, 1], "total-amount": 10, "max-transfer": 3}


def _read(process, balances, t=0):
    return [{"type": "invoke", "f": "read", "value": None,
             "process": process, "time": t},
            {"type": "ok", "f": "read", "value": balances,
             "process": process, "time": t + 1}]


def test_bank_valid():
    h = history(_read(0, {0: 4, 1: 6}) + _read(1, {0: 10, 1: 0}))
    res = bank.checker().check(BANK_TEST, h, {})
    assert res["valid?"] is True
    assert res["read-count"] == 2


def test_bank_wrong_total():
    h = history(_read(0, {0: 4, 1: 7}))
    res = bank.checker().check(BANK_TEST, h, {})
    assert res["valid?"] is False
    assert res["errors"]["wrong-total"]["count"] == 1
    assert res["errors"]["wrong-total"]["first"]["total"] == 11


def test_bank_negative_balance():
    h = history(_read(0, {0: 12, 1: -2}))
    res = bank.checker().check(BANK_TEST, h, {})
    assert res["valid?"] is False
    assert "negative-value" in res["errors"]
    # allowed when negative-balances? is set
    res2 = bank.checker({"negative-balances?": True}).check(
        BANK_TEST, h, {})
    assert res2["valid?"] is True


def test_bank_nil_balance_and_unexpected_key():
    res = bank.checker().check(
        BANK_TEST, history(_read(0, {0: None, 1: 10})), {})
    assert res["valid?"] is False and "nil-balance" in res["errors"]
    res = bank.checker().check(
        BANK_TEST, history(_read(0, {7: 10})), {})
    assert res["valid?"] is False and "unexpected-key" in res["errors"]


def test_bank_generator_shape():
    t = {**BANK_TEST, "accounts": [0, 1, 2]}
    with gen.fixed_rng(1):
        ops = sim.quick(sim.n_plus_nemesis_context(2),
                        gen.clients(gen.limit(50, bank.generator())))
    assert len(ops) == 50
    for o in ops:
        if o["f"] == "transfer":
            v = o["value"]
            assert v["from"] != v["to"]
            assert 1 <= v["amount"] <= 5


# -- long fork --------------------------------------------------------------

def _lf_read(process, kvs, t):
    txn = [["r", k, v] for k, v in kvs]
    return [{"type": "invoke", "f": "read",
             "value": [["r", k, None] for k, _ in kvs],
             "process": process, "time": t},
            {"type": "ok", "f": "read", "value": txn,
             "process": process, "time": t + 1}]


def _lf_write(process, k, t):
    txn = [["w", k, 1]]
    return [{"type": "invoke", "f": "write", "value": txn,
             "process": process, "time": t},
            {"type": "ok", "f": "write", "value": txn,
             "process": process, "time": t + 1}]


def test_long_fork_detects_fork():
    h = history(
        _lf_write(0, 0, 0) + _lf_write(1, 1, 2)
        + _lf_read(2, [(0, 1), (1, None)], 4)     # sees x, not y
        + _lf_read(3, [(0, None), (1, 1)], 6))    # sees y, not x
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] is False
    assert len(res["forks"]) == 1


def test_long_fork_valid_history():
    h = history(
        _lf_write(0, 0, 0) + _lf_write(1, 1, 2)
        + _lf_read(2, [(0, 1), (1, None)], 4)
        + _lf_read(3, [(0, 1), (1, 1)], 6))
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] is True
    assert res["reads-count"] == 2


def test_long_fork_multiple_writes_unknown():
    h = history(_lf_write(0, 0, 0) + _lf_write(1, 0, 2))
    res = long_fork.checker(2).check({}, h, {})
    assert res["valid?"] == "unknown"
    assert res["error"][0] == "multiple-writes"


def test_long_fork_group_math():
    assert long_fork.group_for(2, 5) == [4, 5]
    assert long_fork.group_for(3, 3) == [3, 4, 5]
    with gen.fixed_rng(7):
        txn = long_fork.read_txn_for(2, 4)
    assert sorted(m[1] for m in txn) == [4, 5]


def test_long_fork_generator():
    with gen.fixed_rng(3):
        ops = sim.quick(sim.n_plus_nemesis_context(3),
                        gen.clients(gen.limit(30, long_fork.generator(2))))
    assert len(ops) == 30
    writes = [o for o in ops if o["f"] == "write"]
    reads = [o for o in ops if o["f"] == "read"]
    assert writes and reads
    # writes hit fresh keys
    written = [o["value"][0][1] for o in writes]
    assert len(set(written)) == len(written)
    # reads cover whole groups
    for o in reads:
        ks = {m[1] for m in o["value"]}
        assert len(ks) == 2


# -- causal -----------------------------------------------------------------

def _c_op(process, f, v, pos, link, t):
    return [{"type": "invoke", "f": f, "value": None if f != "write" else v,
             "process": process, "time": t,
             "position": pos, "link": link},
            {"type": "ok", "f": f, "value": v, "process": process,
             "time": t + 1, "position": pos, "link": link}]


def test_causal_valid_chain():
    h = history(
        _c_op(0, "read-init", 0, 10, "init", 0)
        + _c_op(0, "write", 1, 11, 10, 2)
        + _c_op(0, "read", 1, 12, 11, 4)
        + _c_op(0, "write", 2, 13, 12, 6)
        + _c_op(0, "read", 2, 14, 13, 8))
    res = causal.check().check({}, h, {})
    assert res["valid?"] is True


def test_causal_broken_link():
    h = history(
        _c_op(0, "read-init", 0, 10, "init", 0)
        + _c_op(0, "write", 1, 11, 99, 2))  # links to unseen position
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False
    assert "Cannot link" in res["error"]


def test_causal_stale_read():
    h = history(
        _c_op(0, "read-init", 0, 10, "init", 0)
        + _c_op(0, "write", 1, 11, 10, 2)
        + _c_op(0, "read", 0, 12, 11, 4))  # reads old value 0 after w1
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False
    assert "can't read" in res["error"]


def test_causal_write_out_of_order():
    h = history(_c_op(0, "write", 2, 10, "init", 0))  # expected 1
    res = causal.check().check({}, h, {})
    assert res["valid?"] is False


# -- causal reverse ---------------------------------------------------------

def test_causal_reverse_detects_missing_predecessor():
    # w1 acked before w2 invoked; a read sees 2 but not 1
    h = history([
        {"type": "invoke", "f": "write", "value": 1, "process": 0,
         "time": 0},
        {"type": "ok", "f": "write", "value": 1, "process": 0, "time": 1},
        {"type": "invoke", "f": "write", "value": 2, "process": 1,
         "time": 2},
        {"type": "ok", "f": "write", "value": 2, "process": 1, "time": 3},
        {"type": "invoke", "f": "read", "value": None, "process": 2,
         "time": 4},
        {"type": "ok", "f": "read", "value": [2], "process": 2, "time": 5},
    ])
    res = causal_reverse.checker().check({}, h, {})
    assert res["valid?"] is False
    assert res["errors"][0]["missing"] == [1]


def test_causal_reverse_concurrent_writes_ok():
    # w1 and w2 concurrent: seeing either alone is fine
    h = history([
        {"type": "invoke", "f": "write", "value": 1, "process": 0,
         "time": 0},
        {"type": "invoke", "f": "write", "value": 2, "process": 1,
         "time": 1},
        {"type": "ok", "f": "write", "value": 1, "process": 0, "time": 2},
        {"type": "ok", "f": "write", "value": 2, "process": 1, "time": 3},
        {"type": "invoke", "f": "read", "value": None, "process": 2,
         "time": 4},
        {"type": "ok", "f": "read", "value": [2], "process": 2, "time": 5},
    ])
    res = causal_reverse.checker().check({}, h, {})
    assert res["valid?"] is True


# -- adya g2 ----------------------------------------------------------------

def test_adya_g2_checker():
    def ins(process, k, ab, typ, t):
        return [{"type": "invoke", "f": "insert", "value": KV(k, ab),
                 "process": process, "time": t},
                {"type": typ, "f": "insert", "value": KV(k, ab),
                 "process": process, "time": t + 1}]

    # key 0: both inserts succeed (G2!) — key 1: only one does
    h = history(ins(0, 0, [1, None], "ok", 0)
                + ins(1, 0, [None, 2], "ok", 2)
                + ins(2, 1, [3, None], "ok", 4)
                + ins(3, 1, [None, 4], "fail", 6))
    res = adya.g2_checker().check({}, h, {})
    assert res["valid?"] is False
    assert res["illegal"] == {0: 2}
    assert res["key-count"] == 2
    assert res["legal-count"] == 1

    h2 = history(ins(2, 1, [3, None], "ok", 0)
                 + ins(3, 1, [None, 4], "fail", 2))
    res2 = adya.g2_checker().check({}, h2, {})
    assert res2["valid?"] is True


def test_adya_generator_two_inserts_per_key():
    g = adya.g2_gen()
    ops = sim.quick(sim.n_plus_nemesis_context(4),
                    gen.clients(gen.limit(8, g)))
    by_key = {}
    for o in ops:
        assert o["f"] == "insert"
        by_key.setdefault(o["value"].key, []).append(o["value"].value)
    for k, vals in by_key.items():
        assert len(vals) <= 2
        ids = [x for pair in vals for x in pair if x is not None]
        assert len(ids) == len(set(ids))  # globally unique ids


# -- linearizable register --------------------------------------------------

def test_linearizable_register_bundle():
    t = linearizable_register.test({"nodes": ["a", "b"],
                                   "per-key-limit": 10})
    with gen.fixed_rng(5):
        ops = sim.quick(sim.n_plus_nemesis_context(8),
                        gen.clients(gen.limit(40, t["generator"])))
    assert len(ops) == 40
    assert {o["f"] for o in ops} <= {"read", "write", "cas"}
    # end-to-end check of a tiny valid keyed history
    h = history([
        {"type": "invoke", "f": "write", "value": KV(0, 3), "process": 0,
         "time": 0},
        {"type": "ok", "f": "write", "value": KV(0, 3), "process": 0,
         "time": 1},
        {"type": "invoke", "f": "read", "value": KV(0, None), "process": 1,
         "time": 2},
        {"type": "ok", "f": "read", "value": KV(0, 3), "process": 1,
         "time": 3},
    ])
    res = t["checker"].check({}, h, {})
    assert res["valid?"] is True


def test_causal_test_bundle_builds():
    t = causal.test({"time-limit": 1})
    assert t["generator"] is not None and t["checker"] is not None


def test_causal_reverse_workload_builds():
    w = causal_reverse.workload({"nodes": ["a", "b"], "per-key-limit": 5})
    with gen.fixed_rng(2):
        ops = sim.quick(sim.n_plus_nemesis_context(2),
                        gen.clients(gen.limit(10, w["generator"])))
    assert len(ops) == 10


def test_bank_test_bundle_builds():
    t = bank.test()
    assert t["accounts"] == list(range(8))
    assert t["generator"] is not None


def test_linearizable_register_reads_in_every_group():
    # reserve must be positional within each key group's thread range:
    # every key's history needs read coverage, not just group 0's
    t = linearizable_register.test({"nodes": ["a"], "per-key-limit": 12})
    with gen.fixed_rng(13):
        ops = sim.quick(sim.n_plus_nemesis_context(4),
                        gen.clients(gen.limit(48, t["generator"])))
    by_key = {}
    for o in ops:
        by_key.setdefault(o["value"].key, []).append(o["f"])
    assert len(by_key) >= 2
    for k, fs in by_key.items():
        assert "read" in fs, f"key {k} got no reads: {fs}"


def test_linearizable_register_tiny_per_key_limit():
    t = linearizable_register.test({"nodes": ["a"], "per-key-limit": 1})
    with gen.fixed_rng(1):
        ops = sim.quick(sim.n_plus_nemesis_context(2),
                        gen.clients(gen.limit(6, t["generator"])))
    assert len(ops) == 6  # limit 1 per key, never 0


def test_bank_test_merges_opts():
    t = bank.test({"accounts": [0, 1], "total-amount": 10})
    assert t["accounts"] == [0, 1]
    assert t["total-amount"] == 10
    assert t["max-transfer"] == 5  # default retained


# -- additional-graphs workloads (monotonic / sequential / table /
#    comments) run end-to-end under the deterministic simulator against
#    a sequential in-memory store (a legal strict serialization, so
#    every checker must say valid) -----------------------------------------


def _register_complete(store):
    """Fill r mops from the store; a 'w' with a nil value writes its
    key's current value + 1 (the monotonic increment contract)."""
    def complete(ctx, invoke):
        out = dict(invoke)
        out["type"] = "ok"
        val = []
        for m in invoke["value"]:
            f, k, v = m[0], m[1], m[2]
            if f == "r":
                val.append(["r", k, store.get(k)])
            else:
                x = v if v is not None else (store.get(k) or 0) + 1
                store[k] = x
                val.append(["w", k, x])
        out["value"] = val
        return out
    return complete


def _run_workload(w, complete, n=60, concurrency=3, seed=7):
    with gen.fixed_rng(seed):
        h = sim.simulate(sim.n_plus_nemesis_context(concurrency),
                         gen.clients(gen.limit(n, w["generator"])),
                         complete)
    return w["checker"].check({}, history(h), {})


def test_monotonic_end_to_end():
    w = monotonic.workload()
    res = _run_workload(w, _register_complete({}))
    assert res["valid?"] is True, res
    assert res["txn-count"] == 60


def test_monotonic_detects_stale_read():
    # an inc completes (x: nil -> 1); a later read still sees nil
    h = history(
        [{"type": "invoke", "f": "inc",
          "value": [["r", 0, None], ["w", 0, None]], "process": 0,
          "time": 0},
         {"type": "ok", "f": "inc",
          "value": [["r", 0, None], ["w", 0, 1]], "process": 0,
          "time": 1},
         {"type": "invoke", "f": "read", "value": [["r", 0, None]],
          "process": 1, "time": 2},
         {"type": "ok", "f": "read", "value": [["r", 0, None]],
          "process": 1, "time": 3}])
    res = monotonic.workload()["checker"].check({}, h, {})
    assert res["valid?"] is False
    assert "G-single-realtime" in res["anomaly-types"]


def test_sequential_end_to_end():
    w = sequential.workload()
    res = _run_workload(w, _register_complete({}))
    assert res["valid?"] is True, res


def test_sequential_generator_orders_pair_writes():
    with gen.fixed_rng(3):
        ops = sim.quick_ops(sim.n_plus_nemesis_context(3),
                            gen.clients(gen.limit(
                                40, sequential.generator())))
    first_write = {}
    for o in ops:
        if o["type"] != "invoke":
            continue
        if o["f"] == "write":
            k = o["value"][0][1]
            first_write.setdefault(k, o["process"])
        else:
            # reads probe the pair in reverse order
            ks = [m[1] for m in o["value"]]
            assert ks[0] == ks[1] + 1
    for i in range(0, max(first_write, default=0), 2):
        if i + 1 in first_write:
            # the second write of a pair comes from the thread that
            # wrote the first (process may bump after crashes, but the
            # quick harness never crashes)
            assert first_write[i + 1] == first_write[i]


def test_sequential_detects_reversed_visibility():
    # process 0 writes k0 then k1; a reader sees k1's value but not k0
    h = history(
        _lf_write(0, 0, 0)[:1]
        + [{"type": "ok", "f": "write", "value": [["w", 0, 1]],
            "process": 0, "time": 1},
           {"type": "invoke", "f": "write", "value": [["w", 1, 1]],
            "process": 0, "time": 2},
           {"type": "ok", "f": "write", "value": [["w", 1, 1]],
            "process": 0, "time": 3},
           {"type": "invoke", "f": "read",
            "value": [["r", 1, None], ["r", 0, None]], "process": 1,
            "time": 4},
           {"type": "ok", "f": "read",
            "value": [["r", 1, 1], ["r", 0, None]], "process": 1,
            "time": 5}])
    res = sequential.workload()["checker"].check({}, h, {})
    assert res["valid?"] is False
    assert "G-single-process" in res["anomaly-types"]


def _table_complete(created):
    def complete(ctx, invoke):
        out = dict(invoke)
        if invoke["f"] == "create-table":
            created.add(invoke["value"])
            out["type"] = "ok"
        elif invoke["value"][0] in created:
            out["type"] = "ok"
        else:
            out["type"] = "fail"
            out["error"] = ["table-missing", invoke["value"][0]]
        return out
    return complete


def test_table_end_to_end():
    w = table.workload()
    res = _run_workload(w, _table_complete(set()))
    assert res["valid?"] is True, res
    assert res["table-count"] >= 1


def test_table_detects_missing_after_create():
    h = history(
        [{"type": "invoke", "f": "create-table", "value": 0,
          "process": 0, "time": 0},
         {"type": "ok", "f": "create-table", "value": 0, "process": 0,
          "time": 1},
         {"type": "invoke", "f": "insert", "value": [0, 7],
          "process": 1, "time": 2},
         {"type": "fail", "f": "insert", "value": [0, 7], "process": 1,
          "time": 3, "error": ["table-missing", 0]}])
    res = table.checker().check({}, h, {})
    assert res["valid?"] is False
    assert len(res["missing-after-create"]) == 1


def test_table_allows_racing_insert_failure():
    # the insert was invoked before the create completed: no anomaly
    h = history(
        [{"type": "invoke", "f": "create-table", "value": 0,
          "process": 0, "time": 0},
         {"type": "invoke", "f": "insert", "value": [0, 7],
          "process": 1, "time": 1},
         {"type": "ok", "f": "create-table", "value": 0, "process": 0,
          "time": 2},
         {"type": "fail", "f": "insert", "value": [0, 7], "process": 1,
          "time": 3, "error": ["table-missing", 0]}])
    assert table.checker().check({}, h, {})["valid?"] is True


def _comments_complete(store):
    def complete(ctx, invoke):
        out = dict(invoke)
        out["type"] = "ok"
        if invoke["f"] == "write":
            store.add(invoke["value"])
        else:
            out["value"] = sorted(store)
        return out
    return complete


def test_comments_end_to_end():
    w = comments.workload()
    res = _run_workload(w, _comments_complete(set()))
    assert res["valid?"] is True, res
    assert res["read-count"] + res["write-count"] > 0


def test_comments_detects_realtime_gap():
    # write 0 completes before write 1 begins; a read concurrent with
    # write 0 sees 1 but not 0 — a pure ordering gap, not a stale read
    h = history(
        [{"type": "invoke", "f": "write", "value": 0, "process": 0,
          "time": 0},
         {"type": "invoke", "f": "read", "value": None, "process": 2,
          "time": 1},
         {"type": "ok", "f": "write", "value": 0, "process": 0,
          "time": 2},
         {"type": "invoke", "f": "write", "value": 1, "process": 1,
          "time": 3},
         {"type": "ok", "f": "write", "value": 1, "process": 1,
          "time": 4},
         {"type": "ok", "f": "read", "value": [1], "process": 2,
          "time": 5}])
    res = comments.checker().check({}, h, {})
    assert res["valid?"] is False
    assert len(res["realtime-gaps"]) == 1


def test_comments_detects_stale_read():
    # write 0 completed before the read even began, yet it's missing
    h = history(
        [{"type": "invoke", "f": "write", "value": 0, "process": 0,
          "time": 0},
         {"type": "ok", "f": "write", "value": 0, "process": 0,
          "time": 1},
         {"type": "invoke", "f": "read", "value": None, "process": 2,
          "time": 2},
         {"type": "ok", "f": "read", "value": [], "process": 2,
          "time": 3}])
    res = comments.checker().check({}, h, {})
    assert res["valid?"] is False
    assert len(res["stale-reads"]) == 1


def test_comments_concurrent_miss_is_legal():
    # both writes overlap the read: seeing either subset is fine
    h = history(
        [{"type": "invoke", "f": "write", "value": 0, "process": 0,
          "time": 0},
         {"type": "invoke", "f": "write", "value": 1, "process": 1,
          "time": 1},
         {"type": "invoke", "f": "read", "value": None, "process": 2,
          "time": 2},
         {"type": "ok", "f": "write", "value": 0, "process": 0,
          "time": 3},
         {"type": "ok", "f": "write", "value": 1, "process": 1,
          "time": 4},
         {"type": "ok", "f": "read", "value": [1], "process": 2,
          "time": 5}])
    assert comments.checker().check({}, h, {})["valid?"] is True
