"""Self-chaos harness (ISSUE 20): fault schedules, chaos genomes,
oracles, the guided-vs-random A/B, and the back-to-back fault pins.

The headline pins:
  * at a fixed seed and budget, the coverage-guided search reaches the
    fault-DURING-recovery-replay conjunction that pure-random sampling
    misses — the compound failure path the harness exists for;
  * on the clean tree every oracle stays green across both arms;
  * a mutation test (the recovery replay rung silently skipped) is
    caught by the verdict-identity oracle and the failing schedule
    shrinks to <= 3 events.
"""

from __future__ import annotations

import json
import os
import random
import threading

import pytest

from jepsen_tpu import _platform, models, service, store
from jepsen_tpu.chaos import (ChaosConfig, ChaosEvent, ChaosGenome,
                              run_chaos)
from jepsen_tpu.chaos import genome as genome_mod
from jepsen_tpu.chaos import oracles as oracles_mod
from jepsen_tpu.chaos.driver import _Chaos, replay_conjunction
from jepsen_tpu.checker import streaming, synth
from jepsen_tpu.search.coverage import extract_chaos_coverage

MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8
FRONTIER = 128
CKPT = 2
TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


def _canon(x):
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def _strip(d, extra=()):
    return _canon({k: v for k, v in d.items()
                   if k not in TIMING + tuple(extra)})


def _jops(h):
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


def _solo(ops, **kw):
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT,
                            **kw)
    for op in ops:
        s.feed(op)
    return s.finish()


def _wgl_spec(**over):
    sp = {"kind": "wgl", "model": service.model_spec(MODEL),
          "chunk-entries": CHUNK, "slots": SLOTS, "engine": "sort",
          "frontier": FRONTIER, "checkpoint-every": CKPT}
    sp.update(over)
    return sp


# -- _platform.FaultSchedule ------------------------------------------------

def test_schedule_relative_triggers_fire_in_order():
    """Event i+1 arms only after event i fires: oom at dispatch hit 2,
    then compile 1 hit AFTER that — hits 1..4 inject at 2 and 3."""
    sched = _platform.FaultSchedule([
        _platform.FaultEvent("oom", "s/*", 2),
        _platform.FaultEvent("compile", "s/*", 1)])
    _platform.install_fault_schedule(sched)
    kinds = []
    for _ in range(4):
        try:
            _platform.maybe_inject_fault("s/a")
            kinds.append(None)
        except _platform.InjectedFault as e:
            kinds.append(e.kind)
    assert kinds == [None, "oom", "compile", None]
    assert [k for (k, _s, _a) in sched.fired] == ["oom", "compile"]


def test_schedule_bitflip_consumes_staging_hits_only():
    import numpy as np
    sched = _platform.FaultSchedule([
        _platform.FaultEvent("bitflip", "s/*", 2)])
    _platform.install_fault_schedule(sched)
    a = np.zeros((4, 4), np.int32)
    # dispatch hits do not advance a bitflip event
    for _ in range(5):
        _platform.maybe_inject_fault("s/a")
    assert not sched.fired
    assert _platform.maybe_corrupt("s/a", a) is a
    flipped = _platform.maybe_corrupt("s/a", a)
    assert flipped is not a and (flipped != a).sum() == 1
    assert [k for (k, _s, _a) in sched.fired] == ["bitflip"]


def test_schedule_site_pattern_and_from_clauses():
    sched = _platform.FaultSchedule.from_clauses(["oom@s/a:1"])
    _platform.install_fault_schedule(sched)
    _platform.maybe_inject_fault("s/b")      # pattern miss: no fire
    with pytest.raises(_platform.InjectedFault):
        _platform.maybe_inject_fault("s/a")
    assert sched.fired == [("oom", "s/a", 1)]


def test_schedule_cleared_by_reset():
    _platform.install_fault_schedule(_platform.FaultSchedule(
        [_platform.FaultEvent("oom", "*", 1)]))
    _platform.reset_fault_injection()
    assert _platform.current_fault_schedule() is None
    _platform.maybe_inject_fault("s/a")      # nothing installed


def test_env_clause_still_injects(monkeypatch):
    """The env form stays back-compatible alongside schedules."""
    monkeypatch.setenv(_platform.FAULT_INJECT_ENV, "oom@s/a:2")
    _platform.reset_fault_injection()
    _platform.maybe_inject_fault("s/a")
    with pytest.raises(_platform.InjectedFault):
        _platform.maybe_inject_fault("s/a")


# -- genomes ----------------------------------------------------------------

def test_genome_json_round_trip_preserves_order():
    g = ChaosGenome(seed=9, workload="register", ops=128, events=(
        ChaosEvent("oom", 2), ChaosEvent("kill-recover", 40),
        ChaosEvent("bitflip", 1)))
    g2 = ChaosGenome.from_dict(json.loads(json.dumps(g.to_dict())))
    assert g2 == g and g2.key() == g.key()
    swapped = ChaosGenome.from_dict({**g.to_dict(), "events": list(
        reversed(g.to_dict()["events"]))})
    assert swapped.key() != g.key()


def test_mutators_stay_in_bounds():
    rng = random.Random(7)
    g = genome_mod.sample_genome(rng, "register", 128)
    for _ in range(300):
        g = genome_mod.mutate(g, rng)
        assert 1 <= len(g.events) <= genome_mod.MAX_EVENTS
        for e in g.events:
            if e.lifecycle:
                assert 0 <= e.at < g.ops
                assert e.kind in genome_mod.LIFECYCLE_KINDS
            else:
                assert 1 <= e.at <= genome_mod.MAX_AFTER
                assert e.kind in genome_mod.BACKEND_KINDS


def test_shrink_reductions_strictly_smaller():
    g = ChaosGenome(seed=9, workload="register", ops=256, events=(
        ChaosEvent("oom", 8), ChaosEvent("compile", 4)))
    cands = list(genome_mod.shrink_reductions(g))
    assert cands
    for c in cands:
        assert genome_mod.genome_size(c) < genome_mod.genome_size(g)


# -- oracles ----------------------------------------------------------------

def _outcome(**kw):
    base = {"timed-out": False, "deferred": False, "degraded": False,
            "fired": [], "actions": [], "deadline-s": 60.0}
    base.update(kw)
    return base


def test_oracle_verdict_identity_catches_divergence():
    solo = {"valid?": True, "frontier-max": 3, "duration-ms": 9}
    good = {"valid?": True, "frontier-max": 3, "duration-ms": 12,
            "recovered": {"faults": ["oom"], "retries": 1}}
    bad = {"valid?": True, "frontier-max": 4}
    fired = [("oom", "s", 1)]
    assert not oracles_mod.check_oracles(
        {"linear": solo},
        _outcome(results={"linear": good}, fired=fired))
    fails = oracles_mod.check_oracles(
        {"linear": solo},
        _outcome(results={"linear": bad}, fired=fired))
    assert any(f["oracle"] == "verdict-identity" for f in fails)


def test_oracle_violation_missed_is_unconditional():
    solo = {"valid?": False, "frontier-max": 3}
    fails = oracles_mod.check_oracles(
        {"linear": solo},
        _outcome(results={"linear": {"valid?": True}}, degraded=True,
                 fired=[("oom", "s", 1)]))
    assert any(f["oracle"] == "violation-missed" for f in fails)


def test_oracle_stamp_rules():
    solo = {"valid?": True}
    # fired fault, no recovered stamp -> inconsistent
    fails = oracles_mod.check_oracles(
        {"linear": solo},
        _outcome(results={"linear": {"valid?": True}},
                 fired=[("oom", "s", 1)]))
    assert any(f["oracle"] == "stamp-consistency" for f in fails)
    # ... unless a promotion raced the schedule
    assert not oracles_mod.check_oracles(
        {"linear": solo},
        _outcome(results={"linear": {"valid?": True}},
                 fired=[("oom", "s", 1)], actions=["kill-recover"]))
    # nothing injected, no verdict -> inconsistent
    fails = oracles_mod.check_oracles(
        {"linear": solo}, _outcome(results=None, deferred=True))
    assert any(f["oracle"] == "stamp-consistency" for f in fails)


def test_oracle_watchdog_and_resources():
    solo = {"valid?": True}
    fails = oracles_mod.check_oracles(
        {"linear": solo}, _outcome(results=None, **{"timed-out": True}),
        {"fds-before": 8, "fds-after": 9,
         "threads-before": 2, "threads-after": 2})
    got = {f["oracle"] for f in fails}
    assert "watchdog" in got and "resource-leak" in got


# -- coverage ---------------------------------------------------------------

def test_chaos_coverage_distinguishes_replay_conjunction():
    plain = [{"event": "fault", "site": "stream-chunk/t", "kind": "oom",
              "retry": 1}]
    conj = [{"event": "fault", "site": "stream-chunk/t", "kind": "oom",
             "retry": 1},
            {"event": "replay-begin", "site": "stream-chunk/t",
             "from_chunk": 2},
            {"event": "fault", "site": "stream-chunk/t",
             "kind": "compile", "retry": 2}]
    c_plain = extract_chaos_coverage(plain)
    c_conj = extract_chaos_coverage(conj)
    assert c_conj.bits - c_plain.bits
    assert c_conj.overlap_bits > c_plain.overlap_bits
    assert not replay_conjunction(plain)
    assert replay_conjunction(conj)
    closed = conj + [{"event": "replay-end",
                      "site": "stream-chunk/t", "replayed": 64}]
    assert replay_conjunction(closed)   # the hit already landed


# -- back-to-back faults against the live checker (satellite) ---------------

def _hist(seed, n=300):
    return _jops(synth.register_history(n, concurrency=3, values=5,
                                        seed=seed))


@pytest.mark.slow
def test_fault_during_recovery_replay_resumes_correctly():
    """The conjunction itself, pinned solo: a second fault lands
    inside the first fault's recovery replay (relative trigger 1) and
    the stream STILL converges to the uninjected verdict."""
    ops = _hist(61)
    want = _solo(ops)
    probes = []
    _platform.probe_hook = probes.append
    try:
        _platform.install_fault_schedule(_platform.FaultSchedule([
            _platform.FaultEvent("oom", "stream-chunk", 3),
            _platform.FaultEvent("compile", "stream-chunk", 1)]))
        got = _solo(ops)
    finally:
        _platform.probe_hook = None
    assert replay_conjunction(probes), \
        "schedule did not land the second fault inside the replay"
    assert sorted(got["recovered"]["faults"]) == ["compile", "oom"]
    assert _strip(got, ("recovered", "attested")) == \
        _strip(want, ("recovered", "attested"))


@pytest.mark.slow
def test_fault_at_chunk_zero_cold():
    """First-ever dispatch faults: recovery has no checkpoint to
    restore and replays from nothing — still byte-identical."""
    ops = _hist(62)
    want = _solo(ops)
    _platform.install_fault_schedule(_platform.FaultSchedule([
        _platform.FaultEvent("device-lost", "stream-chunk", 1)]))
    got = _solo(ops)
    assert got["recovered"]["faults"] == ["device-lost"]
    assert _strip(got, ("recovered", "attested")) == \
        _strip(want, ("recovered", "attested"))


@pytest.mark.slow
def test_corrupt_manifest_then_fault_during_recover(tmp_path):
    """recover() meets a corrupt resume.json AND a backend fault
    during the cold re-check — resumed-or-honestly-degraded, never
    wrong."""
    ops = _hist(63)
    want = _solo(ops)
    root = str(tmp_path / "st")
    d = os.path.join(root, "t", "0")
    os.makedirs(d)
    with open(os.path.join(d, "journal.jsonl"), "w") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default)
                     + "\n")
    import gzip
    with gzip.open(os.path.join(d, "history.jsonl.gz"), "wt") as fh:
        for op in ops:
            fh.write(json.dumps(op, default=store._json_default)
                     + "\n")
    svcdir = os.path.join(d, "service")
    os.makedirs(svcdir)
    with open(os.path.join(svcdir, "resume.json"), "w") as fh:
        fh.write('{"stream": "t/0", "targets": {"linear"')
    assert store.load_service_resume(d) is None

    _platform.install_fault_schedule(_platform.FaultSchedule([
        _platform.FaultEvent("oom", "stream-chunk/t/0", 2)]))
    svc = service.VerificationService(adaptive=False)
    try:
        names = svc.recover(
            root, spec_fn=lambda _d: {"linear": _wgl_spec()})
        assert names == ["t/0"]
        w = svc._worker("t/0")
        assert w.done.wait(120.0)
        got = dict(w.results)
        if not got:
            got = store.load_streamed_results(d) or {}
        sched = _platform.current_fault_schedule()
        assert [k for (k, _s, _a) in sched.fired] == ["oom"]
        assert _strip(got["linear"], ("recovered", "attested")) == \
            _strip(want, ("recovered", "attested"))
    finally:
        svc.stop()


# -- the loop: clean-tree green, A/B separation, mutation test --------------

@pytest.mark.slow
def test_clean_tree_all_oracles_green():
    r = run_chaos(ChaosConfig(budget=8, seed=5, ops=128,
                              strategy="guided"))
    assert r["schedules"] == 8
    assert r["failures"] == [] and not r["found"]
    assert r["coverage-bits"] > 0


@pytest.mark.slow
def test_guided_vs_random_replay_conjunction_pin():
    """The A/B the harness exists for, at a pinned (seed, budget):
    guided constructs the fault-during-replay conjunction; random,
    drawing from the same event space, never does."""
    guided = run_chaos(ChaosConfig(budget=30, seed=23, ops=128,
                                   strategy="guided"))
    rand = run_chaos(ChaosConfig(budget=30, seed=23, ops=128,
                                 strategy="random"))
    assert guided["failures"] == [] and rand["failures"] == []
    assert guided["found-conjunction"], \
        "guided search no longer reaches the replay conjunction"
    assert guided["conjunction-hits"] >= 3
    assert rand["conjunction-hits"] == 0, \
        "random found the conjunction — the pin lost its separation"
    assert guided["corpus-size"] > 0 and rand["corpus-size"] == 0


@pytest.mark.slow
def test_mutation_skipped_replay_rung_caught_and_shrunk(monkeypatch):
    """Mutation test: silently skip the recovery replay rung (restore
    the checkpoint, never replay the steps-log tail). The
    verdict-identity oracle must catch it and the failing schedule
    must shrink to <= 3 events."""
    orig = streaming.WglStream._restore_and_replay

    def skip_replay(self):
        saved = self._steps_log
        rows0 = self._ckpt[0] if self._ckpt is not None else 0
        kept, got = [], 0
        for a in saved:
            if got + len(a) <= rows0:
                kept.append(a)
                got += len(a)
            elif got < rows0:
                kept.append(a[:rows0 - got])
                got = rows0
            else:
                break
        self._steps_log = kept
        try:
            return orig(self)
        finally:
            self._steps_log = saved

    monkeypatch.setattr(streaming.WglStream, "_restore_and_replay",
                        skip_replay)
    cfg = ChaosConfig(budget=60, seed=3, ops=256,
                      workload="register-corrupt")
    c = _Chaos(cfg)
    g = ChaosGenome(seed=5, workload="register-corrupt", ops=256,
                    events=(ChaosEvent("oom", 2),
                            ChaosEvent("bitflip", 1),
                            ChaosEvent("device-lost", 17)))
    out = c.run_schedule(g)
    assert any(f["oracle"] == "verdict-identity"
               for f in out["failures"]), \
        "broken replay rung not caught by the byte-identity oracle"
    c._record_failure(g, out)
    minimized = c.failures[0]["minimized"]
    assert len(minimized["events"]) <= 3
    assert c.shrink_steps > 0


@pytest.mark.slow
def test_artifacts_round_trip(tmp_path):
    d = str(tmp_path / "art")
    r = run_chaos(ChaosConfig(budget=6, seed=5, ops=128,
                              store_dir=d))
    art = json.load(open(os.path.join(d, "chaos.json")))
    assert art["coverage-digest"] == r["coverage-digest"]
    for entry in art["corpus"]:
        ChaosGenome.from_dict(entry["genome"])   # round-trips
    from jepsen_tpu.search.coverage import CoverageMap
    with open(os.path.join(d, "coverage.bin"), "rb") as f:
        cmap = CoverageMap.decode(f.read())
    assert len(cmap) == r["coverage-bits"]


@pytest.mark.slow
def test_no_thread_growth_across_schedules():
    """The harness's own hygiene: a burst of lifecycle-heavy schedules
    leaves no worker/watcher/server threads behind (the resource-leak
    oracle enforces per-run; this pins the aggregate)."""
    before = threading.active_count()
    r = run_chaos(ChaosConfig(budget=6, seed=13, ops=128,
                              lifecycle_p=0.9, strategy="random"))
    assert r["failures"] == []
    assert threading.active_count() <= before
