"""Control layer: shell escaping, sudo wrapping, DSL scoping, remotes.

Mirrors `jepsen/test/jepsen/control_test.clj` and the escaping semantics
of `control/core.clj:60-153`, but hermetically: the DummyRemote journals
commands instead of SSHing.
"""

import threading

import pytest

from jepsen_tpu import control
from jepsen_tpu.control import core as ctl
from jepsen_tpu.control import dummy, retry, util as cu
from jepsen_tpu.control.core import RemoteError, env, escape, lit


class TestEscape:
    def test_nil_and_empty(self):
        assert escape(None) == ""
        assert escape("") == '""'

    def test_plain(self):
        assert escape("foo") == "foo"
        assert escape(123) == "123"

    def test_specials_quoted(self):
        assert escape("foo bar") == '"foo bar"'
        assert escape("a$b") == '"a\\$b"'
        assert escape('say "hi"') == '"say \\"hi\\""'
        assert escape("back\\slash") == '"back\\\\slash"'
        assert escape("semi;colon") == '"semi;colon"'
        assert escape("glob*") == '"glob*"'

    def test_literal_passthrough(self):
        assert escape(lit("a | b")) == "a | b"

    def test_redirects(self):
        assert escape(">") == ">"
        assert escape(">>") == ">>"
        assert escape("<") == "<"

    def test_sequences(self):
        assert escape(["a", "b c"]) == 'a "b c"'


class TestEnv:
    def test_map(self):
        e = env({"HOME": "/root", "SEEDS": "a b"})
        assert isinstance(e, ctl.Literal)
        assert e.string == 'HOME=/root SEEDS="a b"'

    def test_passthrough(self):
        assert env("X=1").string == "X=1"
        assert env(lit("X=1")).string == "X=1"
        assert env(None) is None

    def test_bad(self):
        with pytest.raises(TypeError):
            env(42)


class TestSudo:
    def test_no_sudo(self):
        a = {"cmd": "ls"}
        assert ctl.wrap_sudo({}, a) == a

    def test_sudo_wraps(self):
        out = ctl.wrap_sudo({"sudo": "root"}, {"cmd": "ls /tmp"})
        assert out["cmd"] == 'sudo -k -S -u root bash -c "ls /tmp"'

    def test_sudo_password_on_stdin(self):
        out = ctl.wrap_sudo({"sudo": "root", "sudo-password": "hunter2"},
                            {"cmd": "ls", "in": "data"})
        assert out["in"] == "hunter2\ndata"


class TestNonzeroExit:
    def test_ok(self):
        r = {"exit": 0, "out": "hi"}
        assert ctl.throw_on_nonzero_exit(r) is r

    def test_throws(self):
        with pytest.raises(RemoteError) as ei:
            ctl.throw_on_nonzero_exit(
                {"exit": 2, "err": "boom", "host": "n1",
                 "action": {"cmd": "false"}})
        assert ei.value.exit == 2


class TestDSL:
    def test_exec_escapes_and_returns_stdout(self):
        r = dummy.DummyRemote(responses={r"\becho": "hello\n"})
        with control.with_remote(r), control.on("n1"):
            assert control.exec_("echo", "hello world") == "hello"
        host, ctx, action = r.log[0]
        assert host == "n1"
        # the DSL wraps every action in the bound dir (default "/")
        assert action["cmd"] == 'cd /; echo "hello world"'

    def test_cd_su_scoping(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            with control.cd("/opt"), control.su():
                control.exec_("ls")
            control.exec_("ls")
        (_, ctx1, _), (_, ctx2, _) = r.log
        assert ctx1 == {"dir": "/opt", "sudo": "root",
                        "sudo-password": None}
        assert ctx2["sudo"] is None and ctx2["dir"] == "/"

    def test_expand_path(self):
        with control.binding(dir="/opt/db"):
            assert control.expand_path("logs") == "/opt/db/logs"
            assert control.expand_path("/abs") == "/abs"

    def test_no_session_raises(self):
        with pytest.raises(RemoteError):
            control.exec_("ls")

    def test_on_nodes_parallel_sessions(self):
        r = dummy.DummyRemote()
        sessions = {n: r.connect({"host": n}) for n in ("n1", "n2", "n3")}
        test = {"nodes": ["n1", "n2", "n3"], "sessions": sessions}

        def f(test, node):
            control.exec_("hostname")
            return control.var("host")

        res = control.on_nodes(test, f)
        assert res == {"n1": "n1", "n2": "n2", "n3": "n3"}
        assert {h for h, _, _ in r.log} == {"n1", "n2", "n3"}

    def test_on_many(self):
        r = dummy.DummyRemote()
        with control.with_remote(r):
            res = control.on_many(["a", "b"], lambda: control.var("host"))
        assert res == {"a": "a", "b": "b"}

    def test_bindings_are_thread_local(self):
        seen = {}

        def worker():
            seen["child"] = control.var("dir")

        with control.binding(dir="/parent"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["child"] == "/"  # child thread gets defaults

    def test_with_ssh(self):
        with control.with_ssh({"username": "admin", "dummy": True,
                               "port": 2222}):
            spec = control.conn_spec()
        assert spec["username"] == "admin"
        assert spec["port"] == 2222
        assert spec["dummy"] is True


class TestUploadDownload:
    def test_upload_str_records_content(self, tmp_path):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            control.upload_str("config contents", "/etc/db.conf")
        assert r.files["/etc/db.conf"] == b"config contents"

    def test_download_logged(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            control.download("/var/log/db.log", "local/")
        assert any("download" in a for _, _, a in r.log)


class TestRetryRemote:
    def test_retries_transport_errors(self):
        calls = {"n": 0}

        class Flaky(dummy.DummyRemote):
            def execute(self, context, action):
                calls["n"] += 1
                if calls["n"] < 3:
                    raise OSError("connection reset")
                return super().execute(context, action)

        f = Flaky()
        # share the prototype so reconnects reuse the same counter
        f.connect = lambda spec: f
        r = retry.RetryRemote(f, backoff_s=0.001).connect({"host": "n1"})
        res = r.execute({}, {"cmd": "ls"})
        assert res["exit"] == 0
        assert calls["n"] == 3

    def test_does_not_retry_nonzero_exit(self):
        calls = {"n": 0}

        class Failing(dummy.DummyRemote):
            def execute(self, context, action):
                calls["n"] += 1
                raise RemoteError("bad", {"exit": 7})

        f = Failing()
        f.connect = lambda spec: f
        r = retry.RetryRemote(f, backoff_s=0.001).connect({"host": "n1"})
        with pytest.raises(RemoteError):
            r.execute({}, {"cmd": "false"})
        assert calls["n"] == 1

    def test_gives_up_after_retries(self):
        class Dead(dummy.DummyRemote):
            def execute(self, context, action):
                raise OSError("nope")

        d = Dead()
        d.connect = lambda spec: d
        r = retry.RetryRemote(d, retries=2, backoff_s=0.001,
                              backoff_cap_s=0.002).connect(
            {"host": "n1"})
        with pytest.raises(RemoteError, match="3 attempts"):
            r.execute({}, {"cmd": "ls"})


class TestBackoff:
    """Capped exponential backoff with decorrelated jitter: N workers
    reconnecting through a healed partition must not retry in
    lockstep."""

    def test_schedule_bounded_by_base_and_cap(self):
        import itertools
        import random
        ds = list(itertools.islice(
            retry.backoff(0.1, 2.0, random.Random(1)), 50))
        assert ds[0] == 0.1  # first delay is the base
        assert all(0.1 <= d <= 2.0 for d in ds)
        assert max(ds) == 2.0  # the cap is reached, never exceeded

    def test_schedule_grows_from_base(self):
        import itertools
        import random
        ds = list(itertools.islice(
            retry.backoff(0.1, 2.0, random.Random(7)), 30))
        # exponential-ish: the tail is well above the base on average
        assert sum(ds[10:]) / len(ds[10:]) > 3 * 0.1

    def test_schedules_decorrelate(self):
        """Two workers with different rng streams must not share a
        schedule — that's the whole point of the jitter."""
        import itertools
        import random
        a = list(itertools.islice(
            retry.backoff(0.1, 2.0, random.Random(1)), 20))
        b = list(itertools.islice(
            retry.backoff(0.1, 2.0, random.Random(2)), 20))
        assert a[1:] != b[1:]

    def test_deterministic_under_seed(self):
        import itertools
        import random
        a = list(itertools.islice(
            retry.backoff(0.05, 1.0, random.Random(3)), 10))
        b = list(itertools.islice(
            retry.backoff(0.05, 1.0, random.Random(3)), 10))
        assert a == b

    def test_nonzero_exit_still_not_retried_with_backoff_config(self):
        """The no-retry-on-nonzero-exit invariant is independent of the
        backoff schedule: a real command result propagates on attempt
        one, whatever the delays would have been."""
        import random
        calls = {"n": 0}

        class Failing(dummy.DummyRemote):
            def execute(self, context, action):
                calls["n"] += 1
                raise RemoteError("bad", {"exit": 7})

        f = Failing()
        f.connect = lambda spec: f
        r = retry.RetryRemote(f, backoff_s=0.5, backoff_cap_s=10.0,
                              rng=random.Random(1)).connect({"host": "n1"})
        with pytest.raises(RemoteError):
            r.execute({}, {"cmd": "false"})
        assert calls["n"] == 1


class TestControlUtil:
    def test_exists_and_ls(self):
        r = dummy.DummyRemote(responses={
            r"\bstat": "ok",
            r"ls -A": "a\nb\n\nc\n",
        })
        with control.with_remote(r), control.on("n1"):
            assert cu.exists("/etc") is True
            assert cu.ls("/etc") == ["a", "b", "c"]
            assert cu.ls_full("/etc") == ["/etc/a", "/etc/b", "/etc/c"]

    def test_write_file_stdin(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            cu.write_file("hello\n", "/tmp/x")
        _, _, action = r.log[0]
        assert action["cmd"].endswith("cat > /tmp/x")
        assert action["in"] == "hello\n"

    def test_write_file_sudo(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"), control.su():
            cu.write_file("x", "/etc/hosts")
        _, _, action = r.log[0]
        assert action["cmd"].startswith("sudo -k -S -u root bash -c ")

    def test_grepkill_pipeline(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            cu.grepkill("mydb", "term")
        cmd = r.log[0][2]["cmd"]
        assert "ps aux | grep mydb | grep -v grep" in cmd
        assert "kill -TERM" in cmd

    def test_start_daemon(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            res = cu.start_daemon(
                {"logfile": "/var/log/db.log", "pidfile": "/run/db.pid",
                 "chdir": "/opt/db", "env": {"PORT": 1234}},
                "/opt/db/bin/db", "--serve")
        assert res == "started"
        cmd = r.log[-1][2]["cmd"]
        assert "start-stop-daemon" in cmd
        assert "--make-pidfile" in cmd
        assert "--startas /opt/db/bin/db" in cmd
        assert "PORT=1234" in cmd
        assert ">> /var/log/db.log" in cmd

    def test_stop_daemon_by_cmd(self):
        r = dummy.DummyRemote()
        with control.with_remote(r), control.on("n1"):
            cu.stop_daemon("/run/db.pid", cmd="db")
        cmds = [a["cmd"] for _, _, a in r.log]
        assert any("killall -9 -w db" in c for c in cmds)

    def test_daemon_running_states(self):
        alive = dummy.DummyRemote(responses={r"\bcat": "42",
                                             r"\bps": "42"})
        with control.with_remote(alive), control.on("n1"):
            assert cu.daemon_running("/run/db.pid") is True

        def no_proc(ctx, action):
            return {"exit": 1, "err": "no such process"}

        dead = dummy.DummyRemote(responses={r"\bcat": "42",
                                            r"\bps": no_proc})
        with control.with_remote(dead), control.on("n1"):
            assert cu.daemon_running("/run/db.pid") is False


class TestFsCache:
    def test_round_trips(self, tmp_path):
        from jepsen_tpu import fs_cache

        fs_cache.set_dir(str(tmp_path / "cache"))
        try:
            assert not fs_cache.cached("k")
            fs_cache.save_string("v1", "k")
            assert fs_cache.load_string("k") == "v1"
            fs_cache.save_data({"a": [1, 2]}, ("nested", "path", 3))
            assert fs_cache.load_data(("nested", "path", 3)) == \
                {"a": [1, 2]}
            # unsafe characters are escaped, not traversed
            fs_cache.save_string("x", "../../evil")
            assert fs_cache.load_string("../../evil") == "x"
            f = fs_cache.file_path("../../evil")
            assert str(tmp_path) in f
        finally:
            fs_cache.set_dir(fs_cache.DEFAULT_DIR)

    def test_fetch_computes_once(self, tmp_path):
        from jepsen_tpu import fs_cache

        fs_cache.set_dir(str(tmp_path / "cache"))
        try:
            calls = {"n": 0}

            def miss():
                calls["n"] += 1
                return b"artifact"

            f1 = fs_cache.fetch("big.tar", miss)
            f2 = fs_cache.fetch("big.tar", miss)
            assert f1 == f2 and calls["n"] == 1
        finally:
            fs_cache.set_dir(fs_cache.DEFAULT_DIR)


class TestReconnect:
    def test_with_conn_reopens_on_error(self):
        from jepsen_tpu import reconnect

        opened = {"n": 0}
        w = reconnect.wrapper(open=lambda: opened.__setitem__(
            "n", opened["n"] + 1) or opened["n"])
        assert w.with_conn(lambda c: c) == 1
        with pytest.raises(ValueError):
            w.with_conn(lambda c: (_ for _ in ()).throw(ValueError()))
        assert w.with_conn(lambda c: c) == 2  # reopened

    def test_concurrent_readers(self):
        from jepsen_tpu import reconnect

        w = reconnect.wrapper(open=lambda: object())
        results = []

        def use():
            results.append(w.with_conn(lambda c: c))

        ts = [threading.Thread(target=use) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(set(map(id, results))) == 1
