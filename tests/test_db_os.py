"""DB/OS protocols: capabilities, cycle retries, tcpdump, debian, faketime.

Hermetic via DummyRemote; mirrors the behaviors of `jepsen/src/jepsen/
{db,os,os/debian,faketime}.clj`.
"""

import pytest

from jepsen_tpu import control, db, faketime
from jepsen_tpu.control import dummy
from jepsen_tpu.os_ import debian, ubuntu


def make_test(remote, nodes=("n1", "n2", "n3")):
    sessions = {n: remote.connect({"host": n}) for n in nodes}
    return {"nodes": list(nodes), "sessions": sessions}


class TestCapabilities:
    def test_noop_supports_nothing(self):
        for cap in ("process", "pause", "primary", "log-files"):
            assert not db.supports(db.noop, cap)

    def test_full_db(self):
        class Full(db.DB, db.Process, db.Pause, db.Primary, db.LogFiles):
            def start(self, test, node): ...
            def kill(self, test, node): ...
            def pause(self, test, node): ...
            def resume(self, test, node): ...
            def primaries(self, test): return []

        d = Full()
        for cap in ("process", "pause", "primary", "log-files"):
            assert db.supports(d, cap)

    def test_tcpdump_has_logfiles(self):
        t = db.tcpdump({"ports": [4000, 5000]})
        assert db.supports(t, "log-files")
        assert t._filter_str() == "port 4000 and port 5000"


class TestCycle:
    def test_teardown_then_setup_all_nodes(self):
        events = []

        class D(db.DB):
            def setup(self, test, node):
                events.append(("setup", node))

            def teardown(self, test, node):
                events.append(("teardown", node))

        r = dummy.DummyRemote()
        test = make_test(r)
        test["db"] = D()
        db.cycle(test)
        downs = [e for e in events if e[0] == "teardown"]
        ups = [e for e in events if e[0] == "setup"]
        assert len(downs) == 3 and len(ups) == 3
        assert events.index(ups[0]) > events.index(downs[-1])

    def test_primary_setup_on_first_node(self):
        prim = []

        class D(db.DB, db.Primary):
            def primaries(self, test):
                return [test["nodes"][0]]

            def setup_primary(self, test, node):
                prim.append(node)

        r = dummy.DummyRemote()
        test = make_test(r)
        test["db"] = D()
        db.cycle(test)
        assert prim == ["n1"]

    def test_retries_on_setup_failed(self):
        attempts = {"n": 0}

        class Flaky(db.DB):
            def setup(self, test, node):
                if node == "n2" and attempts["n"] < 2:
                    attempts["n"] += 1
                    raise db.SetupFailed("not ready")

        r = dummy.DummyRemote()
        test = make_test(r)
        test["db"] = Flaky()
        db.cycle(test)
        assert attempts["n"] == 2

    def test_gives_up_after_three_tries(self):
        class Broken(db.DB):
            def setup(self, test, node):
                raise db.SetupFailed("never works")

        r = dummy.DummyRemote()
        test = make_test(r)
        test["db"] = Broken()
        with pytest.raises(db.SetupFailed):
            db.cycle(test)


class TestTcpdump:
    def test_setup_starts_capture(self):
        r = dummy.DummyRemote()
        t = db.tcpdump({"ports": [2181]})
        with control.with_remote(r), control.on("n1"):
            t.setup({}, "n1")
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("tcpdump" in c0 and "start-stop-daemon" in c0
                   for c0 in cmds)

    def test_teardown_kills_and_cleans(self):
        def no_pid(ctx, action):
            return {"exit": 1, "err": "no such file"}

        r = dummy.DummyRemote(responses={r"\bcat /tmp/jepsen": no_pid})
        t = db.tcpdump({})
        with control.with_remote(r), control.on("n1"):
            t.teardown({}, "n1")
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("rm -rf /tmp/jepsen/tcpdump" in c0 for c0 in cmds)


class TestDebian:
    def test_install_skips_installed(self):
        r = dummy.DummyRemote(responses={
            r"dpkg --get-selections":
                "vim\tinstall\nwget\tinstall\n",
        })
        with control.with_remote(r), control.on("n1"):
            debian.install(["vim", "wget"])
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert not any("apt-get install" in c0 for c0 in cmds)

    def test_install_missing(self):
        r = dummy.DummyRemote(responses={
            r"dpkg --get-selections": "vim\tinstall\n",
            r"\bdate": "1000000000",
            r"\bstat -c": "999999999",
        })
        with control.with_remote(r), control.on("n1"):
            debian.install(["vim", "curl"])
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("apt-get install -y curl" in c0 for c0 in cmds)

    def test_hostfile_rewrite(self):
        r = dummy.DummyRemote(responses={
            r"cat /etc/hosts": "127.0.0.1\tn1.local\n10.0.0.2 n2\n",
        })
        with control.with_remote(r), control.on("n1"):
            debian.setup_hostfile()
        writes = [a for _, _, a in r.log if "cat >" in a.get("cmd", "")]
        assert writes and "127.0.0.1\tlocalhost" in writes[0]["in"]

    def test_installed_version(self):
        r = dummy.DummyRemote(responses={
            r"apt-cache policy":
                "vim:\n  Installed: 2:8.2.2434\n  Candidate: x\n"})
        with control.with_remote(r), control.on("n1"):
            assert debian.installed_version("vim") == "2:8.2.2434"

    def test_ubuntu_setup_heals_net(self):
        healed = []

        class Net:
            def heal(self, test):
                healed.append(True)

        r = dummy.DummyRemote(responses={
            r"dpkg --get-selections":
                "\n".join(f"{p}\tinstall" for p in ubuntu.Ubuntu.packages),
            r"\bdate": "1000000000",
            r"\bstat -c": "1000000000",
            r"cat /etc/hosts": "127.0.0.1\tlocalhost\n",
        })
        with control.with_remote(r), control.on("n1"):
            ubuntu.os.setup({"net": Net()}, "n1")
        assert healed == [True]


class TestFaketime:
    def test_script(self):
        s = faketime.script("/opt/db/bin/db", -5, 1.5)
        assert s.startswith("#!/bin/bash")
        assert 'faketime -m -f "-5s x1.5"' in s
        assert '"$@"' in s

    def test_wrap_moves_original_once(self):
        # stat fails => original not yet moved -> mv happens
        r = dummy.DummyRemote(
            responses={r"\bstat": lambda c, a: {"exit": 1}})
        with control.with_remote(r), control.on("n1"):
            faketime.wrap("/opt/db/bin/db", 0, 2.0)
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("mv /opt/db/bin/db /opt/db/bin/db.no-faketime" in c0
                   for c0 in cmds)
        assert any("chmod a+x /opt/db/bin/db" in c0 for c0 in cmds)

    def test_rand_factor_bounds(self):
        import random

        rng = random.Random(7)
        for _ in range(100):
            v = faketime.rand_factor(2.5, rng)
            hi = 2 / (1 + 1 / 2.5)
            assert hi / 2.5 <= v <= hi

    def test_unwrap_restores(self):
        r = dummy.DummyRemote(responses={r"\bstat": "ok"})
        with control.with_remote(r), control.on("n1"):
            faketime.unwrap("/opt/db/bin/db")
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("mv /opt/db/bin/db.no-faketime /opt/db/bin/db" in c0
                   for c0 in cmds)


def test_debian_install_versions():
    """install() accepts a dict of package -> pinned version, rendered
    as apt's pkg=version syntax (os/debian.clj:81-103 map form)."""
    from jepsen_tpu import control
    from jepsen_tpu.control import dummy
    from jepsen_tpu.os_ import debian

    log = []
    remote = dummy.remote(log=log)
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            debian.install({"zookeeper": "3.4.13", "zookeeperd": "3.4.13"})
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "zookeeper=3.4.13" in cmds
    assert "zookeeperd=3.4.13" in cmds
