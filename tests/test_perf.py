"""Performance tier, mirroring the reference's :perf-tagged tests
(`jepsen/test/jepsen/perf_test.clj`; the >20k ops/s single-thread
claim at `generator.clj:66-70`).

Thresholds sit well under the measured numbers (~31k pure-generator,
~15k full interpreter on an unloaded box) so a loaded CI box doesn't
flake, while a 3x regression still fails."""

import random
import time

import pytest

from jepsen_tpu import core, testkit
from jepsen_tpu import generator as gen
from jepsen_tpu.generator import simulate
import jepsen_tpu.checker


def _mixed_gen(n):
    rng = random.Random(45100)
    return gen.clients(gen.limit(n, gen.mix([
        lambda: {"f": "read"},
        lambda: {"f": "write", "value": rng.randint(0, 4)},
    ])))


@pytest.mark.perf
def test_pure_generator_throughput():
    """Reference parity: >20k ops/s from the pure generator stack,
    single-threaded (`generator.clj:66-70`)."""
    n = 50_000
    ctx = gen.context({"concurrency": 10})
    t0 = time.monotonic()
    h = simulate.quick(ctx, _mixed_gen(n))
    rate = n / (time.monotonic() - t0)
    assert len(h) == n
    print(f"pure generator: {rate:.0f} ops/s")
    assert rate > 12_000, f"generator too slow: {rate:.0f} ops/s"


@pytest.mark.perf
def test_interpreter_throughput(tmp_path):
    """Full round-trip: scheduler + worker threads + 1-slot queues +
    atom client + history journaling."""
    state = testkit.AtomState()
    n = 20_000
    t = testkit.noop_test()
    t.update({
        "name": "perf", "ssh": {"dummy": True},
        "store-dir": str(tmp_path / "store"), "concurrency": 10,
        "db": testkit.atom_db(state),
        "client": testkit.atom_client(state, latency_s=0.0),
        "generator": _mixed_gen(n),
        "checker": jepsen_tpu.checker.unbridled_optimism(),
    })
    t0 = time.monotonic()
    done = core.run(t)
    rate = n / (time.monotonic() - t0)
    invokes = sum(1 for o in done["history"]
                  if o.get("type") == "invoke")
    assert invokes == n
    print(f"interpreter: {rate:.0f} ops/s")
    assert rate > 5_000, f"interpreter too slow: {rate:.0f} ops/s"
