"""Pallas closure-round kernel: correctness pins against the XLA
formulation (interpret mode — no TPU needed), plus the env-gated
end-to-end path through the dense engine."""

import os

import numpy as np
import pytest

from jepsen_tpu import models
from jepsen_tpu.checker import synth
from jepsen_tpu.checker.wgl import _dense_kernel, analysis_tpu
from jepsen_tpu.checker import wgl_pallas


def _xla_round(tb, mf):
    """Independent oracle: the XOR-gather formulation (the dense
    engine's original take_along_axis shape) — deliberately NOT the
    butterfly reshape the pallas kernel uses, so a shared butterfly
    indexing bug cannot cancel out."""
    import jax.numpy as jnp

    P, S, _ = mf.shape
    C = tb.shape[1]
    cols = np.arange(C, dtype=np.int32)
    idx_xor = jnp.asarray(cols[None, :] ^ (1 << np.arange(P))[:, None])
    has_bit = jnp.asarray(
        ((cols[None, :] >> np.arange(P)[:, None]) & 1).astype(bool))
    moved = jnp.einsum("psq,sc->pqc", mf, tb.astype(jnp.float32)) > 0
    shifted = jnp.take_along_axis(moved, idx_xor[:, None, :], axis=2)
    cand = shifted & has_bit[:, None, :]
    return tb.astype(bool) | cand.any(axis=0)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("S,P", [(16, 7), (30, 8)])
def test_closure_round_matches_xla(S, P, seed):
    import jax.numpy as jnp

    C = 1 << P
    rng = np.random.default_rng(seed)
    tb = jnp.asarray(rng.random((S, C)) > 0.9)
    mf = jnp.asarray((rng.random((P, S, S)) > 0.85).astype(np.float32))

    want = np.asarray(_xla_round(tb, mf))
    fn = wgl_pallas.closure_round_fn(S, P, interpret=True)
    got = np.asarray(
        fn(tb.astype(jnp.float32), jnp.swapaxes(mf, 1, 2))) > 0
    assert (got == want).all()


def test_eligibility_bounds():
    assert not wgl_pallas.eligible(32, 6)   # C=64: under one lane tile
    assert wgl_pallas.eligible(32, 7)
    assert not wgl_pallas.eligible(30, 7)   # S not sublane-aligned
    # VMEM gate (4*S*C + P*S*S floats): hardware-validated boundary —
    # S=8 P=16 and S=256 P=10 compile, S=8 P=17 and S=512 P=10 blow VMEM
    assert wgl_pallas.eligible(8, 16)
    assert not wgl_pallas.eligible(8, 17)
    assert wgl_pallas.eligible(256, 10)
    assert not wgl_pallas.eligible(512, 10)


@pytest.mark.parametrize("seed", range(4))
def test_pallas_dense_agrees_with_host_oracle(monkeypatch, seed):
    """Randomized golden agreement for the TPU-default path: the dense
    engine WITH the pallas round must match the host oracle verdict on
    random histories (valid and corrupted), interpret mode off-TPU."""
    from jepsen_tpu.checker.linear import analysis_host

    monkeypatch.setenv("JEPSEN_TPU_PALLAS_CLOSURE", "1")
    _dense_kernel.cache_clear()
    try:
        model = models.cas_register()
        h = synth.register_history(50, concurrency=8, values=5,
                                   crash_rate=0.08, seed=700 + seed)
        a = analysis_tpu(model, h, engine="dense")
        ho = analysis_host(model, h)
        assert a["analyzer"] == "tpu-wgl-dense"
        assert a["valid?"] == ho["valid?"], (seed, a, ho)
        # corrupt() can fabricate out-of-range phantom values that make
        # the dense table ineligible, so the corrupted run uses 'auto'
        # (still the pallas round whenever the dense engine engages)
        bad = synth.corrupt(h, seed=seed)
        ab = analysis_tpu(model, bad, engine="auto")
        hb = analysis_host(model, bad)
        assert ab["valid?"] == hb["valid?"], (seed, ab, hb)
    finally:
        _dense_kernel.cache_clear()


def test_dense_engine_end_to_end_with_pallas_round(monkeypatch):
    """Env-gated: the dense engine must produce identical verdicts with
    the pallas round (interpret mode off-TPU)."""
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_CLOSURE", "1")
    built = []
    orig_fn = wgl_pallas.closure_round_fn

    def counting(S, P, interpret=False):
        built.append((S, P))
        return orig_fn(S, P, interpret=interpret)

    monkeypatch.setattr(wgl_pallas, "closure_round_fn", counting)
    _dense_kernel.cache_clear()
    try:
        model = models.cas_register()
        # values=5 -> S buckets to 8 (sublane-aligned), concurrency 10
        # -> p_exact 11 >= 7: eligible. Tiny history: interpret mode
        # costs ~ms per round
        h = synth.register_history(60, concurrency=10, values=5,
                                   crash_rate=0.1, seed=45100)
        a = analysis_tpu(model, h, engine="dense")
        assert a["analyzer"] == "tpu-wgl-dense"
        assert built, "pallas round was never engaged (eligibility?)"
        # "0" (not unset): pallas is default-on for TPU backends, so
        # only an explicit opt-out guarantees run b is the XLA baseline
        os.environ["JEPSEN_TPU_PALLAS_CLOSURE"] = "0"
        _dense_kernel.cache_clear()
        b = analysis_tpu(model, h, engine="dense")
        os.environ["JEPSEN_TPU_PALLAS_CLOSURE"] = "1"
        assert a["valid?"] == b["valid?"]
        assert a.get("op-count") == b.get("op-count")
    finally:
        _dense_kernel.cache_clear()
