"""Aerospike suite tests: wire-protocol client semantics against the
in-process fake server, DB/nemesis command generation against the
recording dummy remote, and hermetic end-to-end runs for every
workload."""

import threading

import jepsen_tpu.db
import jepsen_tpu.os_
from fake_aerospike import FakeAerospike
from jepsen_tpu import core
from jepsen_tpu.control import dummy
from jepsen_tpu.independent import ktuple
from jepsen_tpu.suites import aerospike, suite
from jepsen_tpu.suites.as_proto import ASError, Conn, RC_GENERATION


def conn_test(f):
    return {"as-conn-fn": lambda n: Conn("127.0.0.1", f.port)}


def test_suite_registry():
    assert suite("aerospike") is aerospike


# -- wire protocol -----------------------------------------------------------

def test_proto_roundtrip():
    f = FakeAerospike()
    try:
        c = Conn("127.0.0.1", f.port)
        assert c.get("jepsen", "cats", 0) is None
        c.put("jepsen", "cats", 0, {"value": 42})
        r = c.get("jepsen", "cats", 0)
        assert r["bins"] == {"value": 42} and r["generation"] == 1
        c.put("jepsen", "cats", 0, {"value": 43}, generation=1)
        assert c.get("jepsen", "cats", 0)["bins"]["value"] == 43
        # stale generation must be rejected
        try:
            c.put("jepsen", "cats", 0, {"value": 99}, generation=1)
            raise AssertionError("generation conflict not raised")
        except ASError as e:
            assert e.code == RC_GENERATION
        assert c.get("jepsen", "cats", 0)["bins"]["value"] == 43
        # append and incr
        c.append("jepsen", "cats", 1, {"value": " 7"})
        c.append("jepsen", "cats", 1, {"value": " 8"})
        assert c.get("jepsen", "cats", 1)["bins"]["value"] == " 7 8"
        c.add("jepsen", "counters", "pounce", {"value": 5})
        c.add("jepsen", "counters", "pounce", {"value": -2})
        assert c.get("jepsen", "counters",
                     "pounce")["bins"]["value"] == 3
        # info protocol
        info = c.info("status", "recluster:")
        assert info["status"] == "ok" and info["recluster:"] == "ok"
        c.close()
    finally:
        f.stop()


def test_generation_cas_race_single_winner():
    """Two concurrent generation-CAS writers: exactly one wins."""
    f = FakeAerospike()
    try:
        c = Conn("127.0.0.1", f.port)
        c.put("jepsen", "cats", 0, {"value": 0})
        g = c.get("jepsen", "cats", 0)["generation"]
        results = []

        def attempt(v):
            c2 = Conn("127.0.0.1", f.port)
            try:
                c2.put("jepsen", "cats", 0, {"value": v}, generation=g)
                results.append(("ok", v))
            except ASError as e:
                results.append(("err", e.code))
            finally:
                c2.close()

        ts = [threading.Thread(target=attempt, args=(v,))
              for v in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        oks = [r for r in results if r[0] == "ok"]
        errs = [r for r in results if r[0] == "err"]
        assert len(oks) == 1 and len(errs) == 1
        assert errs[0][1] == RC_GENERATION
        c.close()
    finally:
        f.stop()


# -- clients ----------------------------------------------------------------

def test_cas_register_client_classification():
    f = FakeAerospike()
    try:
        t = conn_test(f)
        c = aerospike.CasRegisterClient().open(t, "n1")
        assert c.invoke(t, {"type": "invoke", "f": "write",
                            "value": ktuple(0, 3),
                            "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(0, (9, 1)), "process": 0})
        assert r["type"] == "fail" and r["error"] == "value-mismatch"
        r = c.invoke(t, {"type": "invoke", "f": "cas",
                         "value": ktuple(5, (1, 2)), "process": 0})
        assert r["type"] == "fail" and r["error"] == "not-found"
        c.close(t)
    finally:
        f.stop()


def test_client_connection_error_classification():
    """Transport errors: reads fail definitely, writes are :info."""
    t = {"as-conn-fn": lambda n: Conn("127.0.0.1", 1)}
    try:
        aerospike.CasRegisterClient().open(t, "n1")
        raise AssertionError("expected connection failure")
    except OSError:
        pass
    f = FakeAerospike()
    try:
        t = conn_test(f)
        c = aerospike.CasRegisterClient().open(t, "n1")
        f.stop()  # server goes away mid-session
        # shutdown races the in-flight buffers: an op issued right at
        # stop() may still complete; the first op to hit the dead
        # socket must classify correctly
        for _ in range(5):
            r = c.invoke(t, {"type": "invoke", "f": "write",
                             "value": ktuple(0, 1), "process": 0})
            if r["type"] != "ok":
                break
        assert r["type"] == "info", r
        for _ in range(5):
            r = c.invoke(t, {"type": "invoke", "f": "read",
                             "value": ktuple(0, None), "process": 0})
            if r["type"] != "ok":
                break
        assert r["type"] == "fail", r
    finally:
        f.stop()


# -- DB / nemesis command generation -----------------------------------------

def test_db_setup_commands(tmp_path):
    from jepsen_tpu import control
    pkg = tmp_path / "aerospike-server.deb"
    pkg.write_bytes(b"deb")
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2", "n3"], "ssh": {"dummy": True},
            "packages": [str(pkg)]}
    db = aerospike.db({"replication-factor": 2})
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            db.setup(test, "n1")
            db.teardown(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "dpkg -i --force-confnew" in cmds
    assert "roster-set:namespace=jepsen;nodes=n1,n2,n3" in cmds
    assert "recluster" in cmds
    assert "killall" in cmds or "service aerospike stop" in cmds
    # the templated config went over stdin to cat > /etc/...
    stdins = " ".join(a.get("in", "") for _h, _c, a in log
                      if isinstance(a.get("in"), str))
    assert "strong-consistency true" in stdins
    assert "replication-factor 2" in stdins


def test_kill_nemesis_caps_dead_nodes():
    remote = dummy.DummyRemote()
    nodes = ["n1", "n2", "n3", "n4", "n5"]
    sessions = {n: remote.connect({"host": n}) for n in nodes}
    test = {"nodes": nodes, "sessions": sessions,
            "ssh": {"dummy": True}}
    n = aerospike.KillNemesis(signal=9, max_dead=2).setup(test)
    r = n.invoke(test, {"type": "info", "f": "kill",
                        "value": ["n1", "n2", "n3"]})
    killed = [v for v in r["value"].values() if v == "killed"]
    alive = [v for v in r["value"].values() if v == "still-alive"]
    assert len(killed) == 2 and len(alive) == 1
    r2 = n.invoke(test, {"type": "info", "f": "restart",
                         "value": ["n1", "n2", "n3"]})
    assert set(r2["value"].values()) == {"started"}
    r3 = n.invoke(test, {"type": "info", "f": "kill", "value": ["n4"]})
    assert r3["value"]["n4"] == "killed"


# -- hermetic end-to-end runs -------------------------------------------------

def _hermetic(t, f, tmp_path):
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["as-conn-fn"] = lambda n: Conn("127.0.0.1", f.port)
    t["store-dir"] = str(tmp_path / "store")
    return core.run(t)


def test_hermetic_cas_register(tmp_path):
    f = FakeAerospike()
    try:
        t = aerospike.aerospike_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "workload": "cas-register",
            "rate": 200, "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, f, tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_hermetic_counter(tmp_path):
    f = FakeAerospike()
    try:
        t = aerospike.aerospike_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "workload": "counter",
            "rate": 200, "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, f, tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_hermetic_set(tmp_path):
    f = FakeAerospike()
    try:
        t = aerospike.aerospike_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 5,
            "ssh": {"dummy": True}, "workload": "set",
            "rate": 500, "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, f, tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_hermetic_pause(tmp_path):
    """The pause workload drives its own nemesis state machine; against
    the correct fake (SIGSTOP is a no-op through the dummy remote) no
    writes are lost and the set checker passes."""
    f = FakeAerospike()
    try:
        t = aerospike.aerospike_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "workload": "pause",
            "rate": 200, "time-limit": 3,
            "healthy-delay": 0.3, "pause-delay": 0.3})
        done = _hermetic(t, f, tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_hermetic_cas_register_with_full_nemesis(tmp_path):
    """Kill/partition/clock nemesis composition runs against the dummy
    remote; the fake stays consistent so the verdict remains valid."""
    f = FakeAerospike()
    try:
        t = aerospike.aerospike_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "workload": "cas-register",
            "rate": 200, "time-limit": 3, "nemesis-interval": 1,
            "faults": ["partition", "kill"], "no-clocks": True})
        done = _hermetic(t, f, tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
        nem_ops = [o for o in done["history"]
                   if o.get("process") == "nemesis"]
        assert nem_ops, "nemesis emitted no ops"
    finally:
        f.stop()
