"""Coverage-guided scenario search (jepsen_tpu/search/, doc/search.md).

Covers the four layers plus the acceptance demo:

  * generator RNG worker-safety (thread-local fixed_rng; N concurrent
    simulate() calls are bit-identical to serial runs)
  * coverage extraction: stable encodings, disjoint overlaps ->
    disjoint bits, k-gram stability under process renumbering,
    corpus-map novelty/monotonicity/round-trip
  * the genome + mutation engine: determinism, serialization, splice,
    shrink reductions
  * scenarios and the planted-bug executor: healthy runs screen clean
    (the executor linearizes at invoke), the conjunction bug trips the
    stale-read screen exactly when kill AND partition overlap the
    write phase
  * the driver: replayable searches, worker-count independence,
    artifacts, telemetry, escalation; and the pinned A/B demo —
    coverage-guided search finds and shrinks the planted bug at a
    simulation budget where pure random sampling (same seed universe,
    same budget) misses it.

tier0 runs this file with `-k "not ab_demo and not service_escalation"`
(the A/B demo burns a few hundred simulations; the service round trip
builds a verification stream).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import random
import threading

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu import report
from jepsen_tpu.generator.simulate import simulate
from jepsen_tpu.search import coverage as cov_mod
from jepsen_tpu.search import mutate as mut_mod
from jepsen_tpu.search import scenario as scen_mod
from jepsen_tpu.search.coverage import (CoverageMap, extract_coverage)
from jepsen_tpu.search.driver import (SearchConfig, evaluate_genome,
                                      run_search)
from jepsen_tpu.search.mutate import (FaultWindow, Genome, genome_size,
                                      mutate, sample_genome,
                                      shrink_reductions, splice)

# ---------------------------------------------------------------------------
# satellite: thread-local RNG / concurrent simulate determinism
# ---------------------------------------------------------------------------

def _sim_history(seed: int) -> list:
    g = Genome(seed=seed, concurrency=3, workload="register",
               faults=(FaultWindow("kill", 5.0, 2.0),), max_ops=120)
    ctx, ggen, ex, _model = scen_mod.build(g)
    return simulate(ctx, ggen, ex.complete, seed=seed, max_ops=120)


def test_fixed_rng_is_reentrant_and_thread_local():
    with gen.fixed_rng(1):
        a1 = gen.rng.random()
        with gen.fixed_rng(1):
            b1 = gen.rng.random()
        a2 = gen.rng.random()
    with gen.fixed_rng(1):
        c1 = gen.rng.random()
        c2 = gen.rng.random()
    # the inner pin restarted the stream; the outer pin resumed
    assert b1 == a1 == c1
    assert a2 == c2

    # a pin on one thread must not leak into another
    seen = {}

    def worker():
        seen["other"] = gen.rng.random()

    with gen.fixed_rng(7):
        pinned = random.Random(7).random()
        assert gen.rng.random() == pinned
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    with gen.fixed_rng(7):
        # the other thread consumed from ITS stream, not this pin
        assert gen.rng.random() == pinned
    assert "other" in seen


def test_concurrent_simulations_match_serial():
    seeds = [45100 + i for i in range(8)]
    serial = [_sim_history(s) for s in seeds]
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        parallel = list(pool.map(_sim_history, seeds))
    assert serial == parallel
    # and re-running flips nothing (the pinned stream restarts)
    assert serial == [_sim_history(s) for s in seeds]


# ---------------------------------------------------------------------------
# coverage extraction
# ---------------------------------------------------------------------------

def _ops(*events) -> list:
    """Compact history builder: (process, type, f, value) tuples."""
    return [{"process": p, "type": t, "f": f, "value": v}
            for p, t, f, v in events]


def test_identical_histories_identical_encodings():
    hist = _sim_history(45100)
    c1, c2 = extract_coverage(hist), extract_coverage(list(hist))
    assert c1.bits == c2.bits
    m1, m2 = CoverageMap(), CoverageMap()
    m1.add(c1)
    m2.add(c2)
    assert m1.encode() == m2.encode()
    assert m1.digest() == m2.digest()


PINNED_SYNTH_DIGEST = "4dc9420df79753451226782d28d1696a"


def test_coverage_digest_pinned():
    # bits are blake2b-64 over canonical keys: the digest of this
    # fixed synthetic history must never drift across runs, processes,
    # or platforms
    hist = _ops(
        (0, "invoke", "write", 1), (0, "ok", "write", 1),
        ("nemesis", "info", "kill", None),
        (1, "invoke", "read", None),
        ("nemesis", "info", "start", None),
        (1, "ok", "read", 1),
        (0, "invoke", "read", None), (0, "ok", "read", 1),
    )
    m = CoverageMap()
    m.add(extract_coverage(hist))
    assert m.digest() == PINNED_SYNTH_DIGEST


def test_disjoint_overlaps_disjoint_bits():
    base = _ops((0, "invoke", "write", 1), (0, "ok", "write", 1),
                (0, "invoke", "read", None), (0, "ok", "read", 1))
    kill_over_write = _ops(
        ("nemesis", "info", "kill", None),
        (0, "invoke", "write", 1), (0, "ok", "write", 1),
        ("nemesis", "info", "start", None),
        (0, "invoke", "read", None), (0, "ok", "read", 1))
    partition_over_read = _ops(
        (0, "invoke", "write", 1), (0, "ok", "write", 1),
        ("nemesis", "info", "start-partition", None),
        (0, "invoke", "read", None), (0, "ok", "read", 1),
        ("nemesis", "info", "stop-partition", None))
    c0 = extract_coverage(base).bits
    ca = extract_coverage(kill_over_write).bits - c0
    cb = extract_coverage(partition_over_read).bits - c0
    assert ca and cb
    assert not (ca & cb)


def test_kgram_digests_stable_under_renumbering():
    events = [
        (0, "invoke", "write", 1), (1, "invoke", "read", None),
        (0, "ok", "write", 1), (1, "ok", "read", 1),
        (0, "invoke", "read", None), (0, "ok", "read", 1),
        (1, "invoke", "write", 2), (1, "ok", "write", 2),
    ]
    renum = {0: 5, 1: 9}
    renamed = [(renum[p], t, f, v) for p, t, f, v in events]
    assert extract_coverage(_ops(*events)).bits \
        == extract_coverage(_ops(*renamed)).bits


def test_overlap_classes():
    # began-during: window opens while the op is in flight
    h = _ops((0, "invoke", "read", None),
             ("nemesis", "info", "pause", None),
             (0, "ok", "read", None))
    c = extract_coverage(h)
    assert cov_mod._bit("ov", "pause", "read", "began-during") in c.bits
    # within: opens AND closes in flight
    h2 = _ops((0, "invoke", "read", None),
              ("nemesis", "info", "pause", None),
              ("nemesis", "info", "resume", None),
              (0, "ok", "read", None))
    c2 = extract_coverage(h2)
    assert cov_mod._bit("ov", "pause", "read", "within") in c2.bits


def test_conjunction_bits_need_two_kinds():
    one = _ops(("nemesis", "info", "kill", None),
               (0, "invoke", "read", None), (0, "ok", "read", None))
    both = _ops(("nemesis", "info", "kill", None),
                ("nemesis", "info", "start-partition", None),
                (0, "invoke", "read", None), (0, "ok", "read", None))
    pair_bit = cov_mod._bit("ov2", "kill", "partition", "read")
    assert pair_bit not in extract_coverage(one).bits
    assert pair_bit in extract_coverage(both).bits


def test_coverage_map_novelty_and_roundtrip():
    m = CoverageMap()
    a = frozenset({1, 2, 3})
    b = frozenset({3, 4})
    assert m.novel(a) == a
    assert m.add(a) == a
    assert m.novel(b) == {4}
    assert m.add(b) == {4}
    assert m.add(b) == frozenset()
    assert len(m) == 4
    dec = CoverageMap.decode(m.encode())
    assert dec.bits == m.bits
    assert dec.digest() == m.digest()
    with pytest.raises(ValueError):
        CoverageMap.decode(b"\x00" * 7)


def test_fault_vocabulary_pinned_to_nemesis_packages():
    from jepsen_tpu import db as db_
    from jepsen_tpu.nemesis import combined

    # every perf boundary f the combined-nemesis packages declare must
    # be classified by coverage.START_F/STOP_F under the package's own
    # kind name — a new package can't silently fall out of coverage —
    # and scenario's window ops must round-trip through the same table
    kinds = set()
    for pkg in combined.nemesis_packages(
            {"db": db_.noop,
             "faults": ["partition", "kill", "pause", "clock"]}):
        for name, start_fs, stop_fs, _color in pkg["perf"]:
            kinds.add(name)
            for f in start_fs:
                assert cov_mod.START_F.get(f) == name, f
            for f in stop_fs:
                assert cov_mod.STOP_F.get(f) == name, f
    assert kinds == set(mut_mod.FAULT_KINDS)
    assert set(scen_mod.KIND_OPS) == set(mut_mod.FAULT_KINDS)
    for kind, (start_f, stop_f) in scen_mod.KIND_OPS.items():
        assert cov_mod.START_F[start_f] == kind
        assert cov_mod.STOP_F[stop_f] == kind


# ---------------------------------------------------------------------------
# genome + mutation engine
# ---------------------------------------------------------------------------

def test_sample_and_mutate_deterministic():
    a = [sample_genome(random.Random(9), "register", 30.0)
         for _ in range(3)]
    b = [sample_genome(random.Random(9), "register", 30.0)
         for _ in range(3)]
    assert a[0] == b[0] and a == b
    g = a[0]
    m1 = [mutate(g, random.Random(4), 30.0) for _ in range(5)]
    m2 = [mutate(g, random.Random(4), 30.0) for _ in range(5)]
    assert m1 == m2


def test_genome_serialization_roundtrip():
    g = sample_genome(random.Random(3), "phased-register", 60.0,
                      opts={"x": 1}, max_ops=500)
    d = g.to_dict()
    json.loads(json.dumps(d))     # JSON-able
    assert Genome.from_dict(d) == g
    assert Genome.from_dict(d).key() == g.key()


def test_splice_mixes_parent_windows():
    rng = random.Random(11)
    a = Genome(seed=1, concurrency=2, workload="register",
               faults=(FaultWindow("kill", 1.0, 1.0),))
    b = Genome(seed=2, concurrency=3, workload="register",
               faults=(FaultWindow("partition", 2.0, 1.0),))
    kinds = set()
    for _ in range(20):
        child = splice(a, b, rng)
        kinds |= {w.kind for w in child.faults}
        assert len(child.faults) <= mut_mod.MAX_WINDOWS
    assert kinds == {"kill", "partition"}


def test_shrink_reductions_never_grow():
    g = Genome(seed=5, concurrency=5, workload="register",
               faults=(FaultWindow("kill", 10.123, 4.0),
                       FaultWindow("pause", 3.456, 1.0)),
               max_ops=400)
    cands = list(shrink_reductions(g))
    assert cands
    for c in cands:
        assert genome_size(c) <= genome_size(g)
        assert c.key() != g.key()


# ---------------------------------------------------------------------------
# scenarios + the planted-bug executor
# ---------------------------------------------------------------------------

def test_healthy_runs_screen_clean():
    # the executor linearizes at invoke: without a planted bug the
    # screen must stay silent for ANY schedule (no false positives)
    rng = random.Random(20)
    for workload in ("register", "phased-register"):
        for _ in range(4):
            g = sample_genome(rng, workload,
                              scen_mod.default_horizon_s(workload),
                              max_ops=250)
            _h, _c, screen, _m = evaluate_genome(g, bug=None)
            assert screen["valid?"] is True, (workload, g)
            assert screen["suspicion"] == 0


TRIGGER = Genome(
    seed=123, concurrency=3, workload="phased-register",
    faults=(FaultWindow("kill", 44.5, 2.0),
            FaultWindow("partition", 44.6, 2.0)),
    max_ops=600)


def test_planted_bug_requires_the_conjunction_overlap():
    # both kinds over the write phase -> acked write lost -> later
    # reads of the old value are stale -> the screen flags them
    _h, _c, screen, _m = evaluate_genome(
        TRIGGER, bug="lost-write-kill-partition")
    assert screen["violation-count"] > 0
    assert screen["violations"][0]["check"] == "stale-read"

    # one kind alone over the write phase: no drop, no violation
    for lone in ("kill", "partition"):
        g = dataclasses.replace(
            TRIGGER, faults=(FaultWindow(lone, 44.5, 2.0),))
        _h, _c, screen, _m = evaluate_genome(
            g, bug="lost-write-kill-partition")
        assert screen["violation-count"] == 0, lone

    # both kinds, but overlapping the READ phase, not the writes
    g = dataclasses.replace(
        TRIGGER, faults=(FaultWindow("kill", 10.0, 2.0),
                         FaultWindow("partition", 10.5, 2.0)))
    _h, _c, screen, _m = evaluate_genome(
        g, bug="lost-write-kill-partition")
    assert screen["violation-count"] == 0


def test_unknown_workload_raises():
    g = dataclasses.replace(TRIGGER, workload="nope")
    with pytest.raises(ValueError, match="unknown search workload"):
        scen_mod.build(g)


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

_SMOKE = dict(workload="phased-register", strategy="guided",
              bug="lost-write-kill-partition", generations=3,
              population=10, seed=2, max_sims=30, escalate="none")


def _strip_wall(r: dict) -> dict:
    return {k: v for k, v in r.items() if k != "wall-s"}


def test_search_replays_and_ignores_worker_count():
    r1 = run_search(SearchConfig(workers=1, **_SMOKE))
    r4 = run_search(SearchConfig(workers=4, **_SMOKE))
    assert _strip_wall(r1) == _strip_wall(r4)
    assert r1["simulations"] <= 30
    assert r1["coverage-curve"] == sorted(r1["coverage-curve"])


def test_search_artifacts_and_telemetry(tmp_path):
    from jepsen_tpu import telemetry

    before = telemetry.snapshot(prefix="jepsen_tpu_search")
    r = run_search(SearchConfig(workers=2,
                                store_dir=str(tmp_path / "out"),
                                **_SMOKE))
    art = json.loads((tmp_path / "out" / "search.json").read_text())
    assert art["coverage-digest"] == r["coverage-digest"]
    assert art["config"]["workload"] == "phased-register"
    assert len(art["corpus"]) == r["corpus-size"]
    blob = (tmp_path / "out" / "coverage.bin").read_bytes()
    assert CoverageMap.decode(blob).digest() == r["coverage-digest"]
    after = telemetry.snapshot(prefix="jepsen_tpu_search")
    sims = after["jepsen_tpu_search_simulations_total"]
    prev = (before.get("jepsen_tpu_search_simulations_total") or {}) \
        .get("strategy=guided", 0)
    assert sims["strategy=guided"] - prev == r["simulations"]
    assert "jepsen_tpu_search_coverage_bits" in after


def test_search_line_report():
    r = run_search(SearchConfig(workers=2, **_SMOKE))
    line = report.search_line(r)
    assert line.startswith("search (guided):")
    assert f"{r['simulations']} simulations" in line
    assert report.search_line({}) == ""
    assert report.search_line({"screened": True}) == ""


def test_escalate_host_confirms_screen_verdict():
    # seed the search right on the trigger: corpus injection via a
    # one-genome population is overkill, so just confirm directly
    hist, _c, screen, model = evaluate_genome(
        TRIGGER, bug="lost-write-kill-partition")
    assert screen["violation-count"] > 0
    from jepsen_tpu.checker.linear import analysis_host
    res = analysis_host(model, hist, budget_s=30.0)
    assert res["valid?"] is False


def test_search_finds_planted_bug_small_budget():
    # the pinned fast find: seed 2 reaches the planted conjunction
    # bug inside 120 sims (the ab_demo test pins the full A/B)
    r = run_search(SearchConfig(
        workload="phased-register", strategy="guided",
        bug="lost-write-kill-partition", generations=12,
        population=25, seed=2, max_sims=120, workers=4,
        escalate="none"))
    assert r["found"] is True
    v = r["violations"][0]
    assert v["screen-violations"][0]["check"] == "stale-read"
    mini = Genome.from_dict(v["minimized"])
    # the shrunk repro kept only the conjunction that matters
    kinds = {w.kind for w in mini.faults}
    assert kinds == {"kill", "partition"}
    # and it still reproduces
    _h, _c, screen, _m = evaluate_genome(
        mini, bug="lost-write-kill-partition")
    assert screen["violation-count"] > 0
    # minimality: dropping either window kills the repro
    if len(mini.faults) == 2:
        for i in range(2):
            cut = dataclasses.replace(
                mini, faults=mini.faults[:i] + mini.faults[i + 1:])
            _h, _c, s2, _m = evaluate_genome(
                cut, bug="lost-write-kill-partition")
            assert s2["violation-count"] == 0, i


@pytest.mark.parametrize("strategy", ["guided", "random"])
def test_ab_demo_guided_beats_random(strategy):
    # THE acceptance demo, pinned: same seed universe, same 300-sim
    # budget. Guided finds and shrinks the conjunction bug; pure
    # random sampling misses it. (Deterministic: same config -> same
    # search, any worker count, any PYTHONHASHSEED.)
    r = run_search(SearchConfig(
        workload="phased-register", strategy=strategy,
        bug="lost-write-kill-partition", generations=12,
        population=25, seed=2, max_sims=300, workers=4,
        escalate="none"))
    assert r["coverage-curve"] == sorted(r["coverage-curve"])
    if strategy == "guided":
        assert r["found"] is True
        assert r["simulations"] <= 300
        v = r["violations"][0]
        mini = Genome.from_dict(v["minimized"])
        assert {w.kind for w in mini.faults} == {"kill", "partition"}
        assert v["shrink-steps"] > 0
    else:
        assert r["found"] is False
        assert r["simulations"] == 300


def test_service_escalation_roundtrip():
    # the online path: the violating history offered op-by-op through
    # an in-process VerificationService stream, which must return an
    # invalid verdict from its own screen/checker side
    r = run_search(SearchConfig(
        workload="phased-register", strategy="guided",
        bug="lost-write-kill-partition", generations=12,
        population=25, seed=2, max_sims=120, workers=2,
        escalate="service"))
    assert r["found"] is True
    assert r["escalations"] >= 1
    assert r["violations"][0]["confirmed-by"] not in (None, "")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_search_runs(capsys):
    from jepsen_tpu import cli

    with pytest.raises(SystemExit) as ei:
        cli.main(["search", "--workload", "phased-register",
                  "--strategy", "random", "--generations", "2",
                  "--population", "5", "--max-sims", "10",
                  "--seed", "3"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    r = json.loads(out)
    assert r["simulations"] == 10
    assert r["found"] is False


def test_cli_search_rejects_unknown_workload(capsys):
    from jepsen_tpu import cli

    with pytest.raises(SystemExit) as ei:
        cli.main(["search", "--workload", "bogus"])
    assert ei.value.code == 254


def test_search_resume_continues_under_remaining_budget(tmp_path):
    """ISSUE 20 satellite: --resume reloads search.json +
    coverage.bin and continues — restored sims keep charging against
    max_sims, the corpus and coverage map carry over, and the
    generation budget is cumulative."""
    d = str(tmp_path / "out")
    first = run_search(SearchConfig(
        workers=2, store_dir=d, workload="phased-register",
        strategy="guided", bug="lost-write-kill-partition",
        generations=2, population=10, seed=2, max_sims=60,
        escalate="none"))
    assert first["generations-run"] == 2
    resumed = run_search(SearchConfig(
        workers=2, store_dir=d, resume_dir=d,
        workload="phased-register", strategy="guided",
        bug="lost-write-kill-partition", generations=5,
        population=10, seed=3, max_sims=60, escalate="none"))
    # continued, not restarted
    assert resumed["simulations"] > first["simulations"]
    assert resumed["generations-run"] > first["generations-run"]
    assert resumed["simulations"] <= 60
    assert resumed["coverage-bits"] >= first["coverage-bits"]
    assert resumed["corpus-size"] >= first["corpus-size"]
    assert resumed["coverage-curve"][:len(first["coverage-curve"])] \
        == first["coverage-curve"]
    # artifacts rewritten in place reflect the continued run
    art = json.loads((tmp_path / "out" / "search.json").read_text())
    assert art["simulations"] == resumed["simulations"]
    # a workload mismatch is refused before any simulation
    with pytest.raises(ValueError, match="resume workload"):
        run_search(SearchConfig(workload="register", resume_dir=d))
