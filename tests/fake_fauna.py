"""In-process FaunaDB fake: an HTTP server interpreting the JSON query
AST from `jepsen_tpu.suites.fauna_query` over a *versioned* store —
FaunaDB is a temporal database, so `at` reads past snapshots and
`events` lists an instance's version history. Transactions (one POST =
one txn) are serialized under a lock, evaluated sequentially so later
expressions observe earlier writes (the property the internal workload
probes), and rolled back wholesale on `abort`.

Timestamps are zero-padded counters rendered as "<n>Z" so the suite's
strip_time sorting works the same way it does on real RFC-3339 stamps.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Fault(Exception):
    def __init__(self, status: int, code: str, description: str):
        super().__init__(description)
        self.status, self.code, self.description = status, code, description


class Abort(Fault):
    def __init__(self, msg: str):
        super().__init__(400, "transaction aborted", msg)


def _ts_str(n: int) -> str:
    return f"{n:019d}Z"


def _row_key(r) -> str:
    """Canonical row identity for sorting and set algebra."""
    return json.dumps(r, sort_keys=True, default=str)


class DB:
    """The versioned store + AST evaluator."""

    def __init__(self):
        self.classes: dict[str, dict[str, list]] = {}   # name->id->versions
        self.indexes: dict[str, dict] = {}
        self.ts = 0
        self.auto_id = 0
        self.lock = threading.Lock()
        self.fail_hook = None   # expr -> None | (status, code, desc)

    # -- transaction entry ---------------------------------------------------

    def transact(self, expr):
        with self.lock:
            if self.fail_hook is not None:
                f = self.fail_hook(expr)
                if f is not None:
                    raise Fault(*f)
            self.ts += 1
            txn = _Txn(self, self.ts)
            try:
                return txn.eval(expr, {}, None)
            except BaseException:
                txn.rollback()
                raise


class _Txn:
    def __init__(self, db: DB, ts: int):
        self.db = db
        self.ts = ts
        self.undo: list = []    # (class, id, prior version list copy)

    def rollback(self):
        for cls, id_, prior in reversed(self.undo):
            self.db.classes[cls][id_] = prior

    # -- instance store ------------------------------------------------------

    def _versions(self, cls: str, id_: str) -> list:
        return self.db.classes.setdefault(cls, {}).setdefault(id_, [])

    def _live(self, cls: str, id_: str, at: int | None):
        at = self.ts if at is None else at
        data = None
        ts = None
        # read path: never create class/instance entries
        for (vts, vdata) in self.db.classes.get(cls, {}).get(id_, ()):
            if vts > at:
                break
            data, ts = vdata, vts
        return (ts, data) if data is not None else None

    def _write(self, cls: str, id_: str, data):
        vs = self._versions(cls, id_)
        self.undo.append((cls, id_, list(vs)))
        vs.append((self.ts, data))

    def _instance(self, cls: str, id_: str, ts: int, data) -> dict:
        return {"ref": {"class": cls, "id": id_}, "ts": _ts_str(ts),
                "data": data}

    # -- index reads ---------------------------------------------------------

    @staticmethod
    def _field(data: dict, path: list):
        cur = {"data": data}
        for p in path:
            if p == "ref":
                return "ref"
            if not isinstance(cur, dict) or p not in cur:
                return None
            cur = cur[p]
        return cur

    def _match(self, idx: dict, term, at: int | None) -> list:
        src = idx["source"]
        if isinstance(src, dict):
            src = src["class"]
        rows = []
        for id_, _vs in list(self.db.classes.get(src, {}).items()):
            live = self._live(src, id_, at)
            if live is None:
                continue
            ts, data = live
            if idx.get("terms"):
                tvals = [self._field(data, t["field"])
                         for t in idx["terms"]]
                if tvals != [term]:
                    continue
            vals = []
            for v in idx.get("values", []):
                if v["field"] == ["ref"]:
                    vals.append({"class": src, "id": id_})
                else:
                    vals.append(self._field(data, v["field"]))
            if not vals:
                row = {"class": src, "id": id_}
            elif len(vals) == 1:
                row = vals[0]
            else:
                row = vals
            rows.append(row)
        rows.sort(key=_row_key)
        return rows

    # -- evaluator -----------------------------------------------------------

    def eval(self, e, env: dict, at: int | None):
        ev = lambda x: self.eval(x, env, at)  # noqa: E731
        if e is None or isinstance(e, (bool, int, float, str)):
            return e
        if isinstance(e, list):
            return [ev(x) for x in e]
        assert isinstance(e, dict), e

        if "object" in e and len(e) == 1:
            return {k: ev(v) for k, v in e["object"].items()}
        if "var" in e and len(e) == 1:
            return env[e["var"]]
        if "let" in e:
            env = dict(env)
            for binding in e["let"]:
                (k, v), = binding.items()
                env[k] = self.eval(v, env, at)
            return self.eval(e["in"], env, at)
        if "if" in e:
            return ev(e["then"]) if ev(e["if"]) else ev(e["else"])
        if "do" in e:
            out = None
            for x in e["do"]:
                out = ev(x)
            return out
        if "lambda" in e:
            return e     # a function value; applied by map/foreach
        if "map" in e:
            coll = ev(e["collection"])
            items = coll["data"] if isinstance(coll, dict) else coll
            fn = e["map"]
            out = []
            for item in items:
                args = item if isinstance(item, list) else [item]
                env2 = dict(env)
                for p, a in zip(fn["lambda"], args):
                    env2[p] = a
                out.append(self.eval(fn["expr"], env2, at))
            if isinstance(coll, dict):
                return {**coll, "data": out}
            return out
        if "foreach" in e:
            self.eval({"map": e["foreach"],
                       "collection": e["collection"]}, env, at)
            return ev(e["collection"])
        if "time" in e:
            assert e["time"] == "now", e
            return _ts_str(self.ts)
        if "at" in e:
            ts_s = ev(e["at"])
            at2 = int(str(ts_s).rstrip("Z"))
            return self.eval(e["expr"], env, at2)
        if "abort" in e:
            raise Abort(ev(e["abort"]))
        if "add" in e:
            vals = [ev(x) for x in e["add"]]
            return sum(vals)
        if "subtract" in e:
            vals = [ev(x) for x in e["subtract"]]
            out = vals[0]
            for v in vals[1:]:
                out -= v
            return out
        if "lt" in e:
            vals = [ev(x) for x in e["lt"]]
            return all(a < b for a, b in zip(vals, vals[1:]))
        if "equals" in e:
            vals = [ev(x) for x in e["equals"]]
            return all(v == vals[0] for v in vals[1:])
        if "not" in e:
            return not ev(e["not"])
        if "and" in e:
            return all(ev(x) for x in e["and"])
        if "or" in e:
            return any(ev(x) for x in e["or"])
        if "non_empty" in e:
            v = ev(e["non_empty"])
            if isinstance(v, dict):
                v = v.get("data")
            return bool(v)
        if "select" in e:
            return self._select(e, env, at)
        if "exists" in e:
            return self._exists(ev(e["exists"]), at)
        if "get" in e:
            return self._get(ev(e["get"]), at)
        if "create" in e:
            return self._create(ev(e["create"]), ev(e["params"]))
        if "update" in e:
            return self._update(ev(e["update"]), ev(e["params"]))
        if "delete" in e:
            return self._delete(ev(e["delete"]))
        if "create_class" in e:
            params = ev(e["create_class"])
            self.db.classes.setdefault(params["name"], {})
            return {"class": params["name"]}
        if "create_index" in e:
            params = ev(e["create_index"])
            self.db.indexes[params["name"]] = params
            return {"index": params["name"]}
        if "match" in e:
            return {"@match": ev(e["match"]),
                    "@term": ev(e.get("terms")) if "terms" in e else None}
        if "union" in e or "intersection" in e:
            op_name = "union" if "union" in e else "intersection"
            args = e[op_name]
            if not args:
                raise Fault(400, "invalid expression",
                            f"{op_name} needs at least one set")

            # set semantics throughout, as real Fauna's Union/
            # Intersection (duplicates within an argument set collapse)
            rows_sets = [{_row_key(r)
                          for r in self._set_rows(ev(x), at)}
                         for x in args]
            out = rows_sets[0]
            for ks in rows_sets[1:]:
                out = out | ks if op_name == "union" else out & ks
            return {"@rows": [json.loads(k) for k in sorted(out)]}
        if "singleton" in e:
            r = ev(e["singleton"])
            # the empty set when the doc doesn't exist at the read ts
            return {"@rows": [r] if self._exists(r, at) else []}
        if "events" in e:
            r = ev(e["events"])
            return {"@events": r}
        if "paginate" in e:
            return self._paginate(e, env, at)
        if "class" in e and len(e) == 1:
            return e
        if "index" in e and len(e) == 1:
            return e
        if "ref" in e:
            return {"ref": ev(e["ref"]), "id": str(ev(e["id"]))}
        raise Fault(400, "invalid expression", f"unhandled form {e!r}")

    # -- form implementations ------------------------------------------------

    def _select(self, e, env, at):
        cur = self.eval(e["from"], env, at)
        for p in e["select"]:
            p = self.eval(p, env, at) if isinstance(p, dict) else p
            if isinstance(cur, list) and isinstance(p, int):
                if not 0 <= p < len(cur):
                    return self._default(e, env, at)
                cur = cur[p]
            elif isinstance(cur, dict) and p in cur:
                cur = cur[p]
            else:
                return self._default(e, env, at)
        return cur

    def _default(self, e, env, at):
        if "default" in e:
            return self.eval(e["default"], env, at)
        raise Fault(404, "value not found", "path not found in select")

    def _exists(self, r, at) -> bool:
        if "index" in r:
            return r["index"] in self.db.indexes
        if "class" in r and "id" not in r:
            return r["class"] in self.db.classes
        cls, id_ = r["ref"]["class"], r["id"]
        return self._live(cls, id_, at) is not None

    def _get(self, r, at):
        if "index" in r:
            idx = self.db.indexes.get(r["index"])
            if idx is None:
                raise Fault(404, "instance not found", "no such index")
            return idx
        cls, id_ = r["ref"]["class"], r["id"]
        live = self._live(cls, id_, at)
        if live is None:
            raise Fault(404, "instance not found",
                        f"no instance {cls}/{id_}")
        ts, data = live
        return self._instance(cls, id_, ts, data)

    def _create(self, target, params):
        data = params.get("data", {})
        if "class" in target and "ref" not in target:
            cls = target["class"]
            self.db.auto_id += 1
            id_ = str(10**9 + self.db.auto_id)
        else:
            cls, id_ = target["ref"]["class"], target["id"]
            if self._live(cls, id_, None) is not None:
                raise Fault(400, "instance already exists",
                            f"{cls}/{id_} exists")
        if cls not in self.db.classes:
            raise Fault(400, "invalid ref", f"no class {cls}")
        self._write(cls, id_, data)
        return self._instance(cls, id_, self.ts, data)

    def _update(self, r, params):
        cls, id_ = r["ref"]["class"], r["id"]
        live = self._live(cls, id_, None)
        if live is None:
            raise Fault(404, "instance not found",
                        f"no instance {cls}/{id_}")
        _, data = live
        # versions store the instance's data map; update merges fields
        new = {**data, **params.get("data", {})}
        self._write(cls, id_, new)
        return self._instance(cls, id_, self.ts, new)

    def _delete(self, r):
        cls, id_ = r["ref"]["class"], r["id"]
        live = self._live(cls, id_, None)
        if live is None:
            raise Fault(404, "instance not found",
                        f"no instance {cls}/{id_}")
        self._write(cls, id_, None)
        return self._instance(cls, id_, self.ts, live[1])

    def _set_rows(self, src, at) -> list:
        """Resolve a set value (index match, union/intersection rows,
        or a plain array) to its row list."""
        if isinstance(src, dict) and "@match" in src:
            idx = self.db.indexes.get(src["@match"].get("index"))
            if idx is None:
                raise Fault(404, "instance not found", "no such index")
            return self._match(idx, src["@term"], at)
        if isinstance(src, dict) and "@rows" in src:
            return src["@rows"]
        return src if isinstance(src, list) else [src]

    def _paginate(self, e, env, at):
        src = self.eval(e["paginate"], env, at)
        size = e.get("size", 64)
        after = e.get("after")
        if isinstance(after, dict):
            after = self.eval(after, env, at)
        if isinstance(src, dict) and ("@match" in src or "@rows" in src):
            rows = self._set_rows(src, at)
        elif isinstance(src, dict) and "@events" in src:
            r = src["@events"]
            cls, id_ = r["ref"]["class"], r["id"]
            rows = []
            prev = None
            for (vts, vdata) in self.db.classes.get(cls, {}).get(id_, ()):
                if vts > (self.ts if at is None else at):
                    break
                action = "delete" if vdata is None else \
                    ("create" if prev is None else "update")
                rows.append({"ts": _ts_str(vts), "action": action,
                             "data": vdata})
                prev = vdata
            return {"data": rows[:size]}
        else:
            rows = src if isinstance(src, list) else [src]
        start = int(after) if after is not None else 0
        page = rows[start:start + size]
        out = {"data": page}
        if start + size < len(rows):
            out["after"] = start + size
        return out


class FakeFauna:
    """HTTP wrapper; starts on a random port."""

    def __init__(self):
        self.db = DB()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                try:
                    expr = json.loads(self.rfile.read(n))
                    res = fake.db.transact(expr)
                    body = json.dumps({"resource": res},
                                      default=str).encode()
                    status = 200
                except Fault as f:
                    body = json.dumps({"errors": [{
                        "code": f.code,
                        "description": f.description}]}).encode()
                    status = f.status
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def fail_hook(self):
        return self.db.fail_hook

    @fail_hook.setter
    def fail_hook(self, f):
        self.db.fail_hook = f

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
