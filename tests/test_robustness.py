"""Run survivability (ISSUE 2): op deadlines + wedged-worker
containment, the write-ahead op journal, crash salvage, and bounded
teardown.

Jepsen's value is the history: faults are injected on purpose, so the
harness must survive hung clients and crashed runs without losing the
data it was built to collect. These tests wedge and kill runs on
purpose and assert the history survives.
"""

import json
import os
import random
import threading
import time

import pytest

from jepsen_tpu import checker as jchecker
from jepsen_tpu import client as jclient
from jepsen_tpu import core
from jepsen_tpu import generator as gen
from jepsen_tpu import models, store, testkit
from jepsen_tpu.checker.linear import analysis_host
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history import History
from jepsen_tpu.util import relative_time


class HangingClient(jclient.Client):
    """Wedges forever (well: 30 s, so a broken containment path fails
    the test instead of hanging the suite) on its first invoke; later
    invokes answer ok. Late answers carry 'late' so leakage into the
    history is detectable."""

    def __init__(self, hang_first_n: int = 1, latency_s: float = 0.0):
        self.release = threading.Event()
        self.hang_first_n = hang_first_n
        self.latency_s = latency_s
        self.n = 0
        self.lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        with self.lock:
            self.n += 1
            hang = self.n <= self.hang_first_n
        if hang:
            self.release.wait(30)
            return {**op, "type": "ok", "late": True}
        if self.latency_s:
            time.sleep(self.latency_s)
        return {**op, "type": "ok"}


def hang_test(tmp_path, client, **kw):
    t = testkit.noop_test()
    t.update({
        "store-dir": str(tmp_path / "store"),
        "start-time": store.start_time(),
        "client": client,
    })
    t.update(kw)
    return t


# -- op deadlines + wedged-worker containment -------------------------------

def test_hung_invoke_times_out_journals_info_and_retires_process(tmp_path):
    """Acceptance: a run whose client hangs forever terminates within
    op-timeout + grace, with the hung op journaled as :info and the
    wedged process retired and replaced."""
    client = HangingClient()
    t = hang_test(
        tmp_path, client,
        concurrency=1,
        generator=gen.clients(gen.limit(6, gen.repeat({"f": "read"}))),
    )
    t["op-timeout"] = 0.2
    t0 = time.monotonic()
    with relative_time():
        hist = interpreter.run(t)
    elapsed = time.monotonic() - t0
    try:
        # terminated within op-timeout + grace, nowhere near the 30 s
        # the client would have held its worker
        assert elapsed < 5
        infos = [o for o in hist if o["type"] == "info"]
        assert len(infos) == 1
        assert infos[0]["error"] == ["op-timeout", 0.2]
        # the wedged process was retired: later ops run as process 1
        procs = {o["process"] for o in hist}
        assert procs == {0, 1}
        # the run still consumed every generated op on the replacement
        assert len([o for o in hist if o["type"] == "invoke"]) == 6
        assert len(hist) == 12
        # the synthetic :info is in the journal (flushed immediately)
        j = store.load_journal(t)
        assert [o["error"] for o in j if o["type"] == "info"] == \
            [["op-timeout", 0.2]]
        assert len(j) == len(hist)
    finally:
        client.release.set()


def test_late_completion_from_abandoned_worker_is_discarded(tmp_path):
    """The abandoned worker eventually answers; its late result must be
    discarded, not double-completed into the history."""
    client = HangingClient(latency_s=0.1)
    t = hang_test(
        tmp_path, client,
        concurrency=1,
        generator=gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
    )
    t["op-timeout"] = 0.15
    with relative_time():
        # release the wedged worker mid-run (the replacement is still
        # working through ops 2-4), so its late 'ok' races the rest of
        # the run through the completions queue
        threading.Timer(0.25, client.release.set).start()
        hist = interpreter.run(t)
    assert not any(o.get("late") for o in hist), \
        "late completion from a retired worker leaked into the history"
    h = History(hist)
    # well-formed: the timed-out invoke pairs with its synthetic :info
    assert len(h.pending()) == 0
    assert len([o for o in hist if o["type"] == "info"]) == 1


def test_per_op_deadline_overrides_test_level_timeout(tmp_path):
    client = HangingClient()
    t = hang_test(
        tmp_path, client,
        concurrency=1,
        generator=gen.clients(gen.limit(
            2, gen.repeat({"f": "read", "deadline": 0.15}))),
    )
    # test-level bound is enormous; the per-op deadline must win
    t["op-timeout"] = 3600
    t0 = time.monotonic()
    with relative_time():
        hist = interpreter.run(t)
    elapsed = time.monotonic() - t0
    client.release.set()
    assert elapsed < 5
    infos = [o for o in hist if o["type"] == "info"]
    assert len(infos) == 1
    assert infos[0]["error"] == ["op-timeout", 0.15]


def test_hung_nemesis_is_retired_without_concurrent_invoke(tmp_path):
    """A wedged nemesis invoke times out like a client's, but the single
    shared nemesis object must never be invoked concurrently: later
    nemesis ops complete as :info without touching it."""
    from jepsen_tpu import nemesis as jnemesis

    invokes = []
    release = threading.Event()

    class WedgingNemesis(jnemesis.Nemesis):
        def setup(self, test):
            return self

        def invoke(self, test, op):
            invokes.append(op["f"])
            if op["f"] == "start":
                release.wait(30)
            return dict(op)

    t = hang_test(
        tmp_path, testkit.atom_client(testkit.AtomState(0), latency_s=0),
        concurrency=2,
        nemesis=WedgingNemesis(),
        generator=gen.phases(
            gen.nemesis(gen.once({"type": "info", "f": "start"})),
            gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
        ),
    )
    t["op-timeout"] = 0.2
    t0 = time.monotonic()
    with relative_time():
        hist = interpreter.run(t)
    elapsed = time.monotonic() - t0
    release.set()
    assert elapsed < 5
    # the real nemesis saw only the wedged op — never a concurrent one
    assert invokes == ["start"]
    nem = [o for o in hist if o["process"] == "nemesis"
           and o["type"] == "info" and o.get("error")]
    errors = [o["error"] for o in nem]
    assert ["op-timeout", 0.2] in errors
    assert any(isinstance(e, str) and e.startswith("nemesis-retired")
               for e in errors)
    # client ops were unaffected
    assert len([o for o in hist if o["f"] == "read"
                and o["type"] == "ok"]) == 4


def test_run_without_op_timeout_is_unchanged(tmp_path):
    """No op-timeout configured: ordinary runs behave exactly as
    before (no deadlines, no journal-induced history changes)."""
    state = testkit.AtomState(0)
    t = hang_test(
        tmp_path, testkit.atom_client(state, latency_s=0.0),
        concurrency=3,
        generator=gen.clients(gen.limit(30, gen.repeat({"f": "read"}))),
    )
    with relative_time():
        hist = interpreter.run(t)
    assert len(hist) == 60
    assert all(o["type"] in ("invoke", "ok") for o in hist)


# -- write-ahead journal + crash salvage ------------------------------------

def cas_mix(r):
    def g():
        w = r.random()
        if w < 0.5:
            return {"f": "read"}
        if w < 0.8:
            return {"f": "write", "value": r.randrange(5)}
        return {"f": "cas", "value": [r.randrange(5), r.randrange(5)]}
    return g


def test_crash_salvage_round_trip(tmp_path):
    """Acceptance: a run killed mid-history leaves a journal.jsonl from
    which the partial history is recovered and checked — here via a
    generator that explodes when the nemesis phase starts."""
    base = str(tmp_path / "store")
    state = testkit.AtomState(0)
    r = random.Random(45100)

    def boom():
        raise RuntimeError("nemesis exploded")

    t = testkit.noop_test()
    t.update({
        "name": "salvage",
        "store-dir": base,
        "ssh": {"dummy": True},
        "concurrency": 3,
        "db": testkit.atom_db(state),
        "client": testkit.atom_client(state, latency_s=0.0),
        "generator": gen.phases(
            gen.clients(gen.limit(40, cas_mix(r))),
            gen.nemesis(boom)),
    })
    with pytest.raises(gen.GenException):
        core.run(t)

    d = store.latest(base)
    assert d is not None, "a crashed run must still be `latest`"

    # the WAL survived the crash and replays
    j = store.read_journal(os.path.join(d, "journal.jsonl"))
    assert len(j) == 80  # 40 invokes + 40 completions

    # load_journal over the same run via its test identity
    t2 = {"name": "salvage", "store-dir": base,
          "start-time": os.path.basename(d)}
    j2 = store.load_journal(t2)
    assert list(j2) == list(j)

    # core.run's abort path salvaged history.jsonl.gz from the journal
    loaded = store.load_test(d)
    assert [o["f"] for o in loaded["history"]] == [o["f"] for o in j]

    # ...and the partial history is checkable
    a = analysis_host(models.cas_register(0), loaded["history"])
    assert a["valid?"] is True
    res = jchecker.check_safe(jchecker.stats(), loaded, loaded["history"])
    assert res.get("valid?") is not None


def test_torn_final_journal_line_is_tolerated(tmp_path):
    p = str(tmp_path / "journal.jsonl")
    ops = [{"type": "invoke", "f": "read", "value": None,
            "process": 0, "time": 1},
           {"type": "ok", "f": "read", "value": 3,
            "process": 0, "time": 2}]
    with open(p, "w") as fh:
        for o in ops:
            fh.write(json.dumps(o) + "\n")
        fh.write('{"type": "invoke", "f": "wri')  # SIGKILL mid-write
    j = store.read_journal(p)
    assert len(j) == 2
    assert [o["f"] for o in j] == ["read", "read"]
    # an interrupted *final newline* is also fine
    with open(p, "w") as fh:
        fh.write(json.dumps(ops[0]) + "\n" + json.dumps(ops[1]))
    assert len(store.read_journal(p)) == 2


def test_mid_file_journal_corruption_raises(tmp_path):
    """Only a torn *final* line is a crash artifact; garbage earlier in
    the journal is real damage and must not be silently dropped."""
    p = str(tmp_path / "journal.jsonl")
    with open(p, "w") as fh:
        fh.write('{"type": "invoke", "f": "read"}\n')
        fh.write("garbage{{{\n")
        fh.write('{"type": "ok", "f": "read"}\n')
    with pytest.raises(ValueError, match="not the final line"):
        store.read_journal(p)


def test_load_test_salvages_from_journal_without_test_json(tmp_path):
    """A SIGKILL'd run can die before save_1 ever writes test.json; the
    analyze path reconstructs identity from the dir layout and replays
    the journal."""
    d = tmp_path / "store" / "mytest" / "20260803T000000.000000"
    os.makedirs(d)
    ops = [{"type": "invoke", "f": "read", "value": None,
            "process": 0, "time": 1},
           {"type": "ok", "f": "read", "value": 0,
            "process": 0, "time": 2},
           {"type": "invoke", "f": "write", "value": 1,
            "process": 1, "time": 3}]
    with open(d / "journal.jsonl", "w") as fh:
        for o in ops:
            fh.write(json.dumps(o) + "\n")
    loaded = store.load_test(str(d))
    assert loaded["name"] == "mytest"
    assert loaded["start-time"] == "20260803T000000.000000"
    assert loaded["salvaged-from-journal"] is True
    h = loaded["history"]
    assert [o["f"] for o in h] == ["read", "read", "write"]
    assert [o["index"] for o in h] == [0, 1, 2]  # indexed for checkers
    assert [o["f"] for o in h.pending()] == ["write"]


def test_journal_flushes_info_ops_immediately(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j = store.Journal(p, flush_interval_s=3600)
    j.append({"type": "invoke", "f": "read", "process": 0})
    # plain ops are buffered (flush interval far away)...
    with open(p) as fh:
        assert fh.read() == ""
    # ...but an :info op forces the buffer out: it's exactly the op a
    # post-mortem needs
    j.append({"type": "info", "f": "read", "process": 0,
              "error": "indeterminate"})
    with open(p) as fh:
        assert fh.read().count("\n") == 2
    j.close()


def test_journal_flushes_nemesis_ops_immediately(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j = store.Journal(p, flush_interval_s=3600)
    j.append({"type": "invoke", "f": "start", "process": "nemesis"})
    with open(p) as fh:
        assert fh.read().count("\n") == 1
    j.close()
    # close() is idempotent and appends after close are ignored
    j.close()
    j.append({"type": "ok", "f": "start", "process": "nemesis"})
    with open(p) as fh:
        assert fh.read().count("\n") == 1


def test_interpreter_only_runs_do_not_journal(tmp_path, monkeypatch):
    """Without a prepared store identity (name + start-time) the
    interpreter must not litter ./store with journal files."""
    monkeypatch.chdir(tmp_path)
    t = testkit.noop_test()  # has a name but no start-time
    t.update({
        "concurrency": 2,
        "client": testkit.atom_client(testkit.AtomState(0)),
        "generator": gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
    })
    with relative_time():
        hist = interpreter.run(t)
    assert len(hist) == 8
    assert not os.path.exists(tmp_path / "store")


# -- bounded teardown -------------------------------------------------------

class HangingTeardownClient(jclient.Client):
    """invoke works; teardown wedges (a dead node's socket)."""

    def __init__(self, log):
        self.log = log

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        return {**op, "type": "ok"}

    def teardown(self, test):
        self.log.append("teardown-start")
        threading.Event().wait(30)

    def close(self, test):
        self.log.append("close")


def test_hung_client_teardown_does_not_hang_the_run(tmp_path):
    log = []
    t = testkit.noop_test()
    t.update({
        "name": "hung teardown",
        "store-dir": str(tmp_path / "store"),
        "ssh": {"dummy": True},
        "concurrency": 2,
        "teardown-timeout": 0.3,
        "client": HangingTeardownClient(log),
        "generator": gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
    })
    t0 = time.monotonic()
    done = core.run(t)
    elapsed = time.monotonic() - t0
    assert elapsed < 15, "hung teardown must be abandoned, not awaited"
    assert done["results"]["valid?"] is True
    # teardown was attempted on every node, then abandoned; close still
    # ran — once per node-client plus once per interpreter worker client
    nn = len(t["nodes"])
    assert log.count("teardown-start") == nn
    assert log.count("close") == nn + t["concurrency"]
