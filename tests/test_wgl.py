"""TPU WGL kernel golden tests: the device checker must agree with the host
oracle on every history (the SURVEY's 'golden tests for the TPU kernels:
same history arrays in, same verdicts out')."""

import pytest

from jepsen_tpu import models
from jepsen_tpu import models as m
from jepsen_tpu.checker import synth
from jepsen_tpu.checker.linear import analysis_host
from jepsen_tpu.checker import wgl
from jepsen_tpu.checker.wgl import (SlotOverflow, analysis_tpu,
                                    analysis_tpu_batch, build_steps,
                                    check_batch_sharded,
                                    encode_ops_for_model)
from jepsen_tpu.history import History


def op(type, f, value, process=0, time=0):
    return {"type": type, "f": f, "value": value, "process": process,
            "time": time}


SMALL = dict(frontier=128, slots=32)


# -- literal corpus (mirrors test_linear_host) -------------------------------

CORPUS = [
    ("valid write-read", True, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 0), op("ok", "read", 1, 0)]),
    ("stale read", False, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 0), op("ok", "read", 2, 0)]),
    ("concurrent read old", True, [
        op("invoke", "write", 0, 0), op("ok", "write", 0, 0),
        op("invoke", "write", 1, 0),
        op("invoke", "read", None, 1), op("ok", "read", 0, 1),
        op("ok", "write", 1, 0)]),
    ("concurrent read new", True, [
        op("invoke", "write", 0, 0), op("ok", "write", 0, 0),
        op("invoke", "write", 1, 0),
        op("invoke", "read", None, 1), op("ok", "read", 1, 1),
        op("ok", "write", 1, 0)]),
    ("read after second write", False, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 0), op("ok", "write", 2, 0),
        op("invoke", "read", 1, 1), op("ok", "read", 1, 1)]),
    ("crashed write applied", True, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("info", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 2, 2)]),
    ("crashed write skipped", True, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("info", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 1, 2)]),
    ("failed write must not apply", False, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("fail", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 2, 2)]),
    ("cas chain", True, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "cas", (1, 3), 1), op("ok", "cas", (1, 3), 1),
        op("invoke", "read", None, 0), op("ok", "read", 3, 0)]),
    ("impossible cas", False, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "cas", (2, 3), 1), op("ok", "cas", (2, 3), 1)]),
    ("two concurrent writes read first", True, [
        op("invoke", "write", 1, 0),
        op("invoke", "write", 2, 1),
        op("ok", "write", 1, 0),
        op("ok", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 1, 2)]),
    ("late read of crashed write", True, [
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 9, 3), op("info", "write", 9, 3),
        op("invoke", "write", 2, 0), op("ok", "write", 2, 0),
        op("invoke", "read", None, 1), op("ok", "read", 9, 1)]),
    ("empty", True, []),
]


@pytest.mark.parametrize("name,expect,ops",
                         CORPUS, ids=[c[0] for c in CORPUS])
def test_corpus_register(name, expect, ops):
    hist = History(ops)
    a = analysis_tpu(m.cas_register(), hist, **SMALL)
    assert a["valid?"] is expect, a
    # and it agrees with the host oracle
    assert analysis_host(m.cas_register(), hist)["valid?"] is expect


def test_failing_op_diagnosis():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 1), op("ok", "read", 2, 1)])
    a = analysis_tpu(m.cas_register(), hist, **SMALL)
    assert a["valid?"] is False
    assert a["op"]["f"] == "read" and a["op"]["value"] == 2


def test_mutex_on_device():
    good = History([
        op("invoke", "acquire", None, 0), op("ok", "acquire", None, 0),
        op("invoke", "release", None, 0), op("ok", "release", None, 0),
        op("invoke", "acquire", None, 1), op("ok", "acquire", None, 1)])
    assert analysis_tpu(m.mutex(), good, **SMALL)["valid?"] is True
    bad = History([
        op("invoke", "acquire", None, 0), op("ok", "acquire", None, 0),
        op("invoke", "acquire", None, 1), op("ok", "acquire", None, 1)])
    assert analysis_tpu(m.mutex(), bad, **SMALL)["valid?"] is False


def test_pending_acquire_not_dropped():
    # a crashed acquire may have taken the lock: a later failed... rather,
    # a later acquire succeeding is only explainable if the crashed one
    # never applied; both verdicts valid. But a crashed acquire followed by
    # an impossible release sequence must still be checked.
    ops = encode_ops_for_model(m.mutex(), History([
        op("invoke", "acquire", None, 0), op("info", "acquire", None, 0)]))
    assert len(ops) == 1  # pending acquire kept (unlike pending reads)


# -- randomized golden agreement --------------------------------------------

@pytest.mark.parametrize("seed", range(8))
def test_random_valid_histories(seed):
    hist = synth.register_history(60, concurrency=4, values=4,
                                  crash_rate=0.05, seed=seed)
    a = analysis_tpu(m.cas_register(), hist, **SMALL)
    assert a["valid?"] is True, a


@pytest.mark.parametrize("seed", range(8))
def test_random_corrupted_histories(seed):
    hist = synth.corrupt(
        synth.register_history(60, concurrency=4, values=4,
                               crash_rate=0.05, seed=seed), seed)
    a = analysis_tpu(m.cas_register(), hist, **SMALL)
    host = analysis_host(m.cas_register(), hist)
    assert a["valid?"] is host["valid?"] is False


@pytest.mark.parametrize("seed", range(4))
def test_random_agreement_mutex(seed):
    hist = synth.mutex_history(40, concurrency=3, seed=seed)
    a = analysis_tpu(m.mutex(), hist, **SMALL)
    host = analysis_host(m.mutex(), hist)
    assert a["valid?"] is host["valid?"], (a, host)


# -- batching & sharding ------------------------------------------------------

def test_batch():
    hists = [synth.register_history(40, concurrency=3, seed=s)
             for s in range(4)]
    hists.append(synth.corrupt(hists[0]))
    rs = analysis_tpu_batch(m.cas_register(), hists, frontier=128, slots=16)
    assert [r["valid?"] for r in rs] == [True, True, True, True, False]
    assert rs[4].get("op") is not None


def test_sharded_over_mesh():
    import jax
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    hists = [synth.register_history(30, concurrency=3, seed=s)
             for s in range(16)]
    all_ok, per_key = check_batch_sharded(m.cas_register(), hists,
                                          frontier=128, slots=16)
    assert all_ok and per_key.all()
    hists[5] = synth.corrupt(hists[5])
    all_ok, per_key = check_batch_sharded(m.cas_register(), hists,
                                          frontier=128, slots=16)
    assert not all_ok
    assert not per_key[5] and per_key[[i for i in range(16) if i != 5]].all()


def test_batch_mixed_slot_buckets_matches_scalar():
    # keys spanning several slot buckets exercise the bucketed dispatch
    # groups; verdicts must match the per-history scalar checker
    hists = [synth.register_history(60, concurrency=3 + (i % 4) * 2,
                                    values=5, crash_rate=0.02, seed=70 + i)
             for i in range(8)]
    hists.append(synth.corrupt(hists[3]))
    rs = analysis_tpu_batch(m.cas_register(), hists)
    scalar = [analysis_tpu(m.cas_register(), h) for h in hists]
    assert [r["valid?"] for r in rs] == [s["valid?"] for s in scalar]
    assert all("duration-ms" in r for r in rs)


def test_batch_zero_budget_reports_unknown_without_dispatch():
    hists = [synth.register_history(60, concurrency=3 + (i % 3) * 3,
                                    seed=i) for i in range(6)]
    rs = analysis_tpu_batch(m.cas_register(), hists, budget_s=0.0)
    assert all(r["valid?"] == "unknown" for r in rs)
    assert all("duration-ms" in r for r in rs)


def test_sharded_mixed_slot_buckets():
    hists = [synth.register_history(50, concurrency=3 + (i % 5),
                                    values=5, crash_rate=0.01, seed=200 + i)
             for i in range(12)]
    all_ok, per_key = check_batch_sharded(m.cas_register(), hists, slots=16)
    assert all_ok and per_key.all()
    hists.append(synth.corrupt(hists[0], seed=3))
    all_ok, per_key = check_batch_sharded(m.cas_register(), hists, slots=16)
    assert not all_ok and not per_key[-1] and per_key[:-1].all()


def test_sharded_forced_sort_sizes_own_slots():
    # a key needing more slots than the caller passed must not blow up
    hists = [synth.register_history(40, concurrency=7, seed=s)
             for s in range(4)]
    all_ok, per_key = check_batch_sharded(m.cas_register(), hists,
                                          slots=4, engine="sort")
    assert all_ok and per_key.all()


# -- slot machinery -----------------------------------------------------------

def test_slot_overflow_detection():
    hist = History(
        [op("invoke", "write", i, i) for i in range(10)])  # 10 pending
    ops = encode_ops_for_model(m.cas_register(), hist)
    with pytest.raises(SlotOverflow):
        build_steps(ops, 4)


def test_slot_overflow_escalates_transparently():
    # 8 fully-concurrent writes need 8 slots; we hand the checker 4 and it
    # must escalate. frontier 4096 covers all 2^8*8 reachable configs, so
    # no truncation nondeterminism.
    hist = History(
        [op("invoke", "write", i, i) for i in range(8)]
        + [op("ok", "write", i, i) for i in range(8)])
    a = analysis_tpu(m.cas_register(), hist, frontier=4096, slots=4)
    assert a["valid?"] is True


# -- chunked execution / budget (long-search checkpointing) ------------------

def test_required_slots():
    from jepsen_tpu.checker.wgl import encode_ops_for_model, required_slots
    h = synth.register_history(200, concurrency=4, values=5,
                               crash_rate=0.0, seed=7)
    ops = encode_ops_for_model(models.cas_register(), h)
    assert 1 <= required_slots(ops) <= 4
    # crashed ops hold slots forever
    h2 = synth.register_history(200, concurrency=4, values=5,
                                crash_rate=0.05, seed=7)
    ops2 = encode_ops_for_model(models.cas_register(), h2)
    assert required_slots(ops2) > required_slots(ops)


def test_chunked_matches_single_call():
    """Chunked execution must agree with the one-shot kernel."""
    h = synth.register_history(400, concurrency=4, values=5,
                               crash_rate=0.01, seed=11)
    a1 = wgl.analysis_tpu(models.cas_register(), h, chunk_entries=10**9)
    a2 = wgl.analysis_tpu(models.cas_register(), h, chunk_entries=64)
    assert a1["valid?"] == a2["valid?"]


def test_budget_returns_unknown():
    """Past the wall-clock budget an undecided search degrades to
    'unknown' rather than hanging."""
    h = synth.register_history(600, concurrency=5, values=5,
                               crash_rate=0.1, seed=3)  # exponential-ish
    a = wgl.analysis_tpu(models.cas_register(), h, frontier=8,
                         chunk_entries=16, budget_s=0.0)
    assert a["valid?"] == "unknown"
    assert "budget" in a["error"]


def test_budget_never_downgrades_completed_search():
    """A search that finishes all entries is definitive even when it
    blew the budget — no 'unknown' for completed valid verdicts."""
    h = synth.register_history(300, concurrency=4, values=5,
                               crash_rate=0.0, seed=5)
    a = wgl.analysis_tpu(models.cas_register(), h, budget_s=0.0,
                         chunk_entries=10**9)
    assert a["valid?"] is True


def test_invalid_verdict_carries_final_paths_and_configs():
    """Device 'invalid' verdicts reconstruct knossos-style explanations
    via a host re-search of the failing prefix (checker.clj:205-216)."""
    h = synth.corrupt(synth.register_history(300, concurrency=4, values=5,
                                             crash_rate=0.0, seed=9))
    a = wgl.analysis_tpu(models.cas_register(), h)
    assert a["valid?"] is False
    assert a["op"] is not None
    assert a["final-paths"], "failure must carry final paths"
    # each path ends with the failing attempt at the culprit op
    for path in a["final-paths"]:
        assert "Inconsistent" in path[-1]["model"] or \
            "inconsistent" in path[-1]["model"].lower()
    assert a["configs"]


def test_explain_off_skips_host_re_search():
    h = synth.corrupt(synth.register_history(300, concurrency=4, values=5,
                                             crash_rate=0.0, seed=9))
    a = wgl.analysis_tpu(models.cas_register(), h, explain=False)
    assert a["valid?"] is False
    assert a["final-paths"] == []


def test_linear_svg_written_to_store(tmp_path):
    from jepsen_tpu.checker.linear import linearizable

    h = synth.corrupt(synth.register_history(200, concurrency=4, values=5,
                                             crash_rate=0.0, seed=13))
    test = {"name": "svgtest", "start-time": "t0",
            "store-dir": str(tmp_path)}
    c = linearizable(models.cas_register(), "auto")
    res = c.check(test, h, {})
    assert res["valid?"] is False
    svg = tmp_path / "svgtest" / "t0" / "linear.svg"
    assert svg.exists()
    body = svg.read_text()
    assert "nonlinearizable" in body and "final paths" in body


def test_competition_mode():
    from jepsen_tpu.checker.linear import linearizable

    good = synth.register_history(300, concurrency=4, values=5,
                                  crash_rate=0.0, seed=21)
    bad = synth.corrupt(good)
    c = linearizable(models.cas_register(), "competition")
    r1 = c.check({}, good, {})
    assert r1["valid?"] is True and r1["competition-winner"] in ("host",
                                                                "tpu")
    r2 = c.check({}, bad, {})
    assert r2["valid?"] is False


def test_competition_host_only_model():
    # a model with no device form competes by just running the host
    from jepsen_tpu.checker.linear import linearizable
    from jepsen_tpu.models import Model

    class Weird(Model):
        device_model = None

        def step(self, op):
            return self

    h = synth.register_history(50, concurrency=3, values=3,
                               crash_rate=0.0, seed=2)
    r = linearizable(Weird(), "competition").check({}, h, {})
    assert r["valid?"] is True


def test_cancel_hook_stops_device_search():
    h = synth.register_history(600, concurrency=5, values=5,
                               crash_rate=0.1, seed=3)
    a = wgl.analysis_tpu(models.cas_register(), h, frontier=8,
                         chunk_entries=16, cancel=lambda: True)
    assert a["valid?"] == "unknown"
    assert "cancelled" in a["error"]


def test_batch_budget_returns_unknown_for_undecided_keys():
    """A zero budget with tiny chunks leaves later keys undecided:
    they must report 'unknown', not stall or claim a verdict."""
    hs = [synth.register_history(400, concurrency=4, values=4,
                                 crash_rate=0.01, seed=s)
          for s in range(4)]
    rs = analysis_tpu_batch(models.cas_register(), hs, frontier=64,
                            slots=16, chunk_entries=8, budget_s=0.0)
    assert len(rs) == 4
    assert all(r["valid?"] in (True, False, "unknown") for r in rs)
    assert any(r["valid?"] == "unknown" for r in rs)


def test_batch_budget_none_still_decides_everything():
    hs = [synth.register_history(200, concurrency=4, values=4,
                                 crash_rate=0.01, seed=s)
          for s in range(3)]
    hs.append(synth.corrupt(hs[0]))
    rs = analysis_tpu_batch(models.cas_register(), hs, frontier=128,
                            slots=16, chunk_entries=64)
    assert [r["valid?"] for r in rs] == [True, True, True, False]


def test_adversarial_history_device_vs_host():
    """The adversarial crashed-write shape must verify on BOTH device
    engines and agree with the host oracle at small scale."""
    h = synth.adversarial_register_history(300, concurrency=4,
                                           crashed_writes=4)
    a = analysis_tpu(models.cas_register(), h, frontier=2048)
    assert a["valid?"] is True and a["analyzer"] == "tpu-wgl-dense"
    s = analysis_tpu(models.cas_register(), h, frontier=2048,
                     engine="sort")
    assert s["valid?"] is True and s["analyzer"] == "tpu-wgl"
    assert analysis_host(models.cas_register(), h)["valid?"] is True
    bad = synth.corrupt(h)
    a2 = analysis_tpu(models.cas_register(), bad, frontier=2048)
    assert a2["valid?"] is False


def test_packed_and_unpacked_dedup_agree():
    """P=16 with small values packs the config into one u32 sort key;
    P=64 forces the multi-word path. Same verdicts either way (pinned
    to the sort engine — auto would route these to the dense kernel)."""
    for seed in (1, 2):
        h = synth.register_history(300, concurrency=5, values=4,
                                   crash_rate=0.02, seed=seed)
        packed = analysis_tpu(models.cas_register(), h, frontier=256,
                              slots=16, engine="sort")
        wide = analysis_tpu(models.cas_register(), h, frontier=256,
                            slots=64, engine="sort")
        assert packed["valid?"] is wide["valid?"] is True
        bad = synth.corrupt(h)
        pb = analysis_tpu(models.cas_register(), bad, frontier=256,
                          slots=16, engine="sort")
        wb = analysis_tpu(models.cas_register(), bad, frontier=256,
                          slots=64, engine="sort")
        assert pb["valid?"] is wb["valid?"] is False
        assert pb["op-index"] == wb["op-index"]


def test_dense_and_sort_engines_agree_on_random_histories():
    for seed in (11, 12, 13):
        h = synth.register_history(250, concurrency=5, values=4,
                                   crash_rate=0.05, seed=seed)
        d = analysis_tpu(models.cas_register(), h)
        s = analysis_tpu(models.cas_register(), h, frontier=1024,
                         engine="sort")
        assert d["analyzer"] == "tpu-wgl-dense"
        assert d["valid?"] is s["valid?"]


def test_negative_register_values():
    """States below -1 must extend the packed/dense state range
    downward, not wrap the u32 key or fall off the dense table."""
    h = [op("invoke", "write", -3, 0), op("ok", "write", -3, 0),
         op("invoke", "read", None, 0), op("ok", "read", -3, 0)]
    for engine in ("dense", "sort"):
        a = analysis_tpu(models.cas_register(), History(h), engine=engine)
        assert a["valid?"] is True, (engine, a)
    bad = [op("invoke", "write", -3, 0), op("ok", "write", -3, 0),
           op("invoke", "read", None, 0), op("ok", "read", -2, 0)]
    for engine in ("dense", "sort"):
        a = analysis_tpu(models.cas_register(), History(bad), engine=engine)
        assert a["valid?"] is False, (engine, a)


# -- new device models: counter / g-set / unordered queue --------------------

@pytest.mark.parametrize("seed", range(4))
def test_counter_device_host_agreement(seed):
    h = synth.counter_history(120, concurrency=4, crash_rate=0.05,
                              seed=seed)
    d = analysis_tpu(m.counter(), h)
    host = analysis_host(m.counter(), h)
    assert d["valid?"] is host["valid?"] is True, (d, host)


def test_counter_catches_bad_read():
    h = [op("invoke", "add", 2, 0), op("ok", "add", 2, 0),
         op("invoke", "read", None, 0), op("ok", "read", 5, 0)]
    d = analysis_tpu(m.counter(), History(h))
    host = analysis_host(m.counter(), History(h))
    assert d["valid?"] is host["valid?"] is False


def test_counter_concurrent_add_read_window():
    # a read overlapping an add may see either value
    h = [op("invoke", "add", 1, 0),
         op("invoke", "read", None, 1), op("ok", "read", 1, 1),
         op("ok", "add", 1, 0),
         op("invoke", "read", None, 1), op("ok", "read", 1, 1)]
    assert analysis_tpu(m.counter(), History(h))["valid?"] is True
    h2 = [op("invoke", "add", 1, 0),
          op("invoke", "read", None, 1), op("ok", "read", 0, 1),
          op("ok", "add", 1, 0)]
    assert analysis_tpu(m.counter(), History(h2))["valid?"] is True


@pytest.mark.parametrize("seed", range(4))
def test_gset_device_host_agreement(seed):
    h = synth.gset_history(120, concurrency=4, seed=seed)
    d = analysis_tpu(m.gset(), h)
    host = analysis_host(m.gset(), h)
    assert d["valid?"] is host["valid?"] is True, (d, host)


def test_gset_catches_phantom_and_lost_elements():
    lost = [op("invoke", "add", 3, 0), op("ok", "add", 3, 0),
            op("invoke", "read", None, 0), op("ok", "read", [], 0)]
    assert analysis_tpu(m.gset(), History(lost))["valid?"] is False
    phantom = [op("invoke", "add", 3, 0), op("ok", "add", 3, 0),
               op("invoke", "read", None, 0),
               op("ok", "read", [3, 4], 0)]
    assert analysis_tpu(m.gset(), History(phantom))["valid?"] is False


def test_gset_large_elements_fall_back_to_host():
    from jepsen_tpu.checker.linear import linearizable
    h = [op("invoke", "add", 1000, 0), op("ok", "add", 1000, 0),
         op("invoke", "read", None, 0), op("ok", "read", [1000], 0)]
    r = linearizable(m.gset()).check({}, History(h), {})
    assert r["valid?"] is True
    assert r["analyzer"] == "host-jit-linear"


@pytest.mark.parametrize("seed", range(4))
def test_uqueue_device_host_agreement(seed):
    h = synth.uqueue_history(120, concurrency=4, seed=seed)
    d = analysis_tpu(m.unordered_queue(), h)
    host = analysis_host(m.unordered_queue(), h)
    assert d["valid?"] is host["valid?"] is True, (d, host)


def test_uqueue_catches_phantom_dequeue():
    h = [op("invoke", "enqueue", 1, 0), op("ok", "enqueue", 1, 0),
         op("invoke", "dequeue", None, 1), op("ok", "dequeue", 2, 1)]
    d = analysis_tpu(m.unordered_queue(), History(h))
    host = analysis_host(m.unordered_queue(), History(h))
    assert d["valid?"] is host["valid?"] is False


def test_uqueue_unordered_ok():
    # dequeue order need not match enqueue order
    h = [op("invoke", "enqueue", 1, 0), op("ok", "enqueue", 1, 0),
         op("invoke", "enqueue", 2, 0), op("ok", "enqueue", 2, 0),
         op("invoke", "dequeue", None, 1), op("ok", "dequeue", 2, 1),
         op("invoke", "dequeue", None, 1), op("ok", "dequeue", 1, 1)]
    assert analysis_tpu(m.unordered_queue(), History(h))["valid?"] is True


def test_uqueue_crashed_dequeue_falls_back_to_host():
    from jepsen_tpu.checker.linear import linearizable
    h = [op("invoke", "enqueue", 1, 0), op("ok", "enqueue", 1, 0),
         op("invoke", "dequeue", None, 1), op("info", "dequeue", None, 1)]
    r = linearizable(m.unordered_queue()).check({}, History(h), {})
    assert r["valid?"] in (True, False)
    assert r["analyzer"] == "host-jit-linear"


def test_counter_negative_read_value_not_confused_with_nil():
    """An observed read of -1 must constrain the search (it is NOT the
    NIL 'unconstrained' sentinel) — false-valid regression."""
    bad = [op("invoke", "read", None, 0), op("ok", "read", -1, 0)]
    d = analysis_tpu(m.counter(), History(bad))
    host = analysis_host(m.counter(), History(bad))
    assert d["valid?"] is host["valid?"] is False
    good = [op("invoke", "add", -1, 0), op("ok", "add", -1, 0),
            op("invoke", "read", None, 0), op("ok", "read", -1, 0)]
    assert analysis_tpu(m.counter(), History(good))["valid?"] is True


def test_uqueue_multiplicity_overflow_falls_back_to_host():
    """16+ outstanding copies of one value would saturate the device
    digit and report a false invalid — must fall back to the host."""
    from jepsen_tpu.checker.linear import linearizable
    h = []
    for i in range(20):
        h.append(op("invoke", "enqueue", 1, 0))
        h.append(op("ok", "enqueue", 1, 0))
    r = linearizable(m.unordered_queue()).check({}, History(h), {})
    assert r["valid?"] is True
    assert r["analyzer"] == "host-jit-linear"


def test_gset_out_of_range_initial_state_falls_back():
    from jepsen_tpu.checker.linear import linearizable
    model = m.GSet(frozenset({40}))
    h = [op("invoke", "read", None, 0), op("ok", "read", [40], 0)]
    r = linearizable(model).check({}, History(h), {})
    assert r["valid?"] is True
    assert r["analyzer"] == "host-jit-linear"


def test_out_of_int32_values_fall_back_to_host():
    """Values beyond int32 can't encode; the checker must fall back,
    not crash with OverflowError."""
    from jepsen_tpu.checker.linear import linearizable
    h = [op("invoke", "add", 2**31, 0), op("ok", "add", 2**31, 0),
         op("invoke", "read", None, 0), op("ok", "read", 2**31, 0)]
    r = linearizable(m.counter()).check({}, History(h), {})
    assert r["valid?"] is True
    assert r["analyzer"] == "host-jit-linear"


def test_uqueue_initial_multiplicity_cap():
    from jepsen_tpu.checker.linear import linearizable
    model = m.UnorderedQueue(frozenset((1, i) for i in range(16)))
    h = [op("invoke", "dequeue", None, 0), op("ok", "dequeue", 1, 0)]
    r = linearizable(model).check({}, History(h), {})
    assert r["valid?"] is True
    assert r["analyzer"] == "host-jit-linear"


def test_forced_dense_engine_error_still_surfaces():
    """engine='dense' on an ineligible history must raise, not be
    silently downgraded to the host search."""
    h = synth.register_history(50, concurrency=3, values=3,
                               crash_rate=0.0, seed=2)
    big = [dict(o) for o in h.ops]
    big[0] = {**big[0], "value": 10**6}
    big[1] = {**big[1], "value": 10**6}
    with pytest.raises(ValueError, match="dense"):
        analysis_tpu(m.cas_register(), History(big), engine="dense")
    # the batch path honors the same contract for single- and multi-key
    # batches (single-key skips the grouped split; multi-key raises
    # inside _dispatch_groups)
    with pytest.raises(ValueError, match="dense"):
        analysis_tpu_batch(m.cas_register(), [History(big)],
                           engine="dense")
    with pytest.raises(ValueError, match="dense"):
        analysis_tpu_batch(m.cas_register(), [History(big), h],
                           engine="dense")


# -- merged-step stream edge cases -------------------------------------------

def test_steps_merge_tail_completions():
    """A history ending in a run of completions gets a final mask-only
    step; merged and unmerged streams agree on the verdict."""
    from jepsen_tpu.checker.wgl import build_steps, event_count
    h = History([
        op("invoke", "write", 1, 0),
        op("invoke", "write", 2, 1),
        op("invoke", "read", None, 2),
        op("ok", "read", 1, 2),
        op("ok", "write", 1, 0),
        op("ok", "write", 2, 1)])
    ops = encode_ops_for_model(m.cas_register(), h)
    merged = build_steps(ops, 8)
    unmerged = build_steps(ops, 8, merge=False)
    assert merged.n < unmerged.n
    assert unmerged.n == event_count(ops)
    # the merged tail step completes the trailing run, no invoke
    assert merged.x[merged.n - 1][1] == -1
    assert merged.x[merged.n - 1][0] != 0
    a = analysis_tpu(m.cas_register(), h, **SMALL)
    assert a["valid?"] is True


def test_all_crashed_ops_verify():
    """Nothing ever completes: every op pends forever; any subset may
    have applied, so the history is trivially linearizable — and the
    stream contains no completion steps at all."""
    h = History([op("invoke", "write", i, i) for i in range(4)]
                + [op("info", "write", i, i) for i in range(4)])
    a = analysis_tpu(m.cas_register(), h, **SMALL)
    assert a["valid?"] is True


def test_blame_matches_host_oracle_on_corrupted_histories():
    """The unmerged blame re-run must name the same culprit op the
    host oracle finds, across engines."""
    for seed in (3, 4, 5):
        h = synth.corrupt(synth.register_history(
            120, concurrency=4, values=4, crash_rate=0.02, seed=seed),
            seed)
        host = analysis_host(m.cas_register(), h)
        # corrupt() writes an out-of-range value, so dense is
        # ineligible here (dense blame is covered by the corpus
        # diagnosis tests); auto routes to the sort engine
        for engine in ("auto", "sort"):
            a = analysis_tpu(m.cas_register(), h, frontier=4096,
                             engine=engine, explain=False)
            assert a["valid?"] is False is host["valid?"]
            assert a["op-index"] == host["op-index"], (engine, seed)


def test_linearizable_checker_passes_engine_options_through():
    """The checker factory exposes the device-engine tunables (the
    knossos plan.md wish: search heuristics as user options)."""
    from jepsen_tpu.checker.linear import linearizable
    from jepsen_tpu.checker.synth import register_history

    h = register_history(120, concurrency=4, values=3, crash_rate=0.0,
                         seed=45100)
    for engine, marker in (("dense", "tpu-wgl-dense"), ("sort", "tpu-wgl")):
        chk = linearizable({"model": models.cas_register(),
                            "engine": engine})
        r = chk.check({"name": "t"}, h, {})
        assert r["valid?"] is True
        assert r["analyzer"] == marker, r
