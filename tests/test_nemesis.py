"""Nemesis stack: grudge calculus, partitioners, net backends, clock
nemesis, node start/stop, composition.

Mirrors `jepsen/test/jepsen/nemesis_test.clj` behaviors, hermetically via
DummyRemote.
"""

import random

import pytest

from jepsen_tpu import control, net
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import dummy
from jepsen_tpu.nemesis import partition as part
from jepsen_tpu.nemesis import time as ntime
from jepsen_tpu.util import majority

NODES = ["n1", "n2", "n3", "n4", "n5"]


def make_test(remote=None, nodes=NODES, netz=None):
    r = remote or dummy.DummyRemote()
    sessions = {n: r.connect({"host": n}) for n in nodes}
    return {"nodes": list(nodes), "sessions": sessions,
            "net": netz if netz is not None else net.noop}, r


class RecordingNet(net.Net, net.PartitionAll):
    def __init__(self):
        self.events = []

    def drop(self, test, src, dest):
        self.events.append(("drop", src, dest))

    def heal(self, test):
        self.events.append(("heal",))

    def drop_all(self, test, grudge):
        self.events.append(("drop-all",
                            {k: set(v) for k, v in grudge.items()}))

    def slow(self, test, **kw):
        self.events.append(("slow",))

    def flaky(self, test):
        self.events.append(("flaky",))

    def fast(self, test):
        self.events.append(("fast",))


class TestGrudges:
    def test_bisect(self):
        assert part.bisect([1, 2, 3, 4]) == ([1, 2], [3, 4])
        assert part.bisect([1, 2, 3, 4, 5]) == ([1, 2], [3, 4, 5])

    def test_split_one(self):
        loner, rest = part.split_one(NODES, loner="n3")
        assert loner == ["n3"]
        assert rest == ["n1", "n2", "n4", "n5"]

    def test_complete_grudge(self):
        g = part.complete_grudge([["n1", "n2"], ["n3", "n4", "n5"]])
        assert g["n1"] == {"n3", "n4", "n5"}
        assert g["n3"] == {"n1", "n2"}
        assert set(g) == set(NODES)

    def test_bridge(self):
        g = part.bridge(NODES)
        # n3 is the bridge: snubs nobody, snubbed by nobody
        assert "n3" not in g
        assert g["n1"] == {"n4", "n5"}
        assert g["n4"] == {"n1", "n2"}

    def test_invert_grudge(self):
        g = part.invert_grudge(
            ["a", "b", "c"], {"a": {"a", "b"}, "b": {"a", "b"}})
        assert g == {"a": {"c"}, "b": {"c"}, "c": {"a", "b"}}

    @pytest.mark.parametrize("n", [3, 4, 5, 6, 7, 9, 12])
    def test_majorities_ring_properties(self, n):
        """Every node sees a majority; no two nodes see the same
        majority (`nemesis.clj:260-275`)."""
        nodes = [f"m{i}" for i in range(n)]
        rng = random.Random(42 + n)
        g = part.majorities_ring(nodes, rng)
        universe = set(nodes)
        views = {}
        for node in nodes:
            visible = universe - set(g.get(node, set()))
            assert node in visible
            assert len(visible) >= majority(n), \
                f"{node} sees only {len(visible)} of {n}"
            views[node] = frozenset(visible)
        if n == 5:
            # exact algorithm: all views distinct
            assert len(set(views.values())) == n


class TestPartitioner:
    def test_start_stop(self):
        rn = RecordingNet()
        test, _ = make_test(netz=rn)
        p = part.partition_halves().setup(test)
        out = p.invoke(test, {"type": "info", "f": "start"})
        assert out["value"][0] == "isolated"
        grudge = out["value"][1]
        assert grudge["n1"] == {"n3", "n4", "n5"}
        assert ("drop-all", {k: set(v) for k, v in grudge.items()}) in \
            rn.events
        out = p.invoke(test, {"type": "info", "f": "stop"})
        assert out["value"] == "network-healed"
        assert rn.events[-1] == ("heal",)

    def test_value_grudge_overrides(self):
        rn = RecordingNet()
        test, _ = make_test(netz=rn)
        p = part.partitioner().setup(test)
        g = {"n1": {"n2"}}
        out = p.invoke(test, {"type": "info", "f": "start", "value": g})
        assert out["value"] == ["isolated", g]

    def test_no_grudge_raises(self):
        rn = RecordingNet()
        test, _ = make_test(netz=rn)
        p = part.partitioner().setup(test)
        with pytest.raises(ValueError):
            p.invoke(test, {"type": "info", "f": "start"})


class TestIptablesNet:
    def test_drop_all_batches_per_node(self):
        r = dummy.DummyRemote(responses={
            r"getent ahosts": lambda c_, a: {
                "out": "10.0.0.9 STREAM x\n"}})
        test, _ = make_test(remote=r, netz=net.iptables)
        net.iptables.drop_all(test, {"n1": {"n2", "n3"}, "n2": set()})
        cmds = [a.get("cmd", "") for h, _, a in r.log
                if h == "n1" and "iptables" in a.get("cmd", "")]
        assert len(cmds) == 1
        assert "-A INPUT -s 10.0.0.9,10.0.0.9 -j DROP -w" in cmds[0]

    def test_heal_flushes(self):
        r = dummy.DummyRemote()
        test, _ = make_test(remote=r, netz=net.iptables)
        net.iptables.heal(test)
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert sum("iptables -F -w" in c0 for c0 in cmds) == 5
        assert sum("iptables -X -w" in c0 for c0 in cmds) == 5

    def test_slow_uses_netem(self):
        r = dummy.DummyRemote()
        test, _ = make_test(remote=r, netz=net.iptables)
        net.iptables.slow(test, mean_ms=100, variance_ms=5)
        cmds = [a.get("cmd", "") for _, _, a in r.log]
        assert any("netem delay 100ms 5ms distribution normal" in c0
                   for c0 in cmds)


class TestComposition:
    def test_compose_by_reflection(self):
        class A(nem.Nemesis):
            def fs(self):
                return {"a"}

            def invoke(self, test, op):
                return {**op, "value": "A"}

        class B(nem.Nemesis):
            def fs(self):
                return {"b"}

            def invoke(self, test, op):
                return {**op, "value": "B"}

        c0 = nem.compose([A(), B()])
        assert c0.invoke({}, {"f": "a"})["value"] == "A"
        assert c0.invoke({}, {"f": "b"})["value"] == "B"
        assert c0.fs() is None or True  # compose exposes routing

    def test_compose_conflict_raises(self):
        class A(nem.Nemesis):
            def fs(self):
                return {"x"}

        with pytest.raises(ValueError, match="incompatible"):
            nem.compose([A(), A()])

    def test_f_map(self):
        class A(nem.Nemesis):
            def fs(self):
                return {"start", "stop"}

            def invoke(self, test, op):
                return {**op, "value": f"handled-{op['f']}"}

        lifted = nem.f_map(lambda f: ("part", f), A())
        out = lifted.invoke({}, {"f": ("part", "start")})
        assert out["f"] == ("part", "start")
        assert out["value"] == "handled-start"
        assert lifted.fs() == {("part", "start"), ("part", "stop")}

    def test_timeout_nemesis(self):
        import time as t

        class Slow(nem.Nemesis):
            def invoke(self, test, op):
                t.sleep(1.0)
                return op

        out = nem.timeout(50, Slow()).invoke({}, {"f": "x"})
        assert out["value"] == "timeout"


class TestNodeStartStopper:
    def test_start_stop_cycle(self):
        r = dummy.DummyRemote()
        test, _ = make_test(remote=r)
        calls = []

        def start(t, node):
            calls.append(("start", node))
            return ["killed", "db"]

        def stop(t, node):
            calls.append(("stop", node))
            return ["restarted", "db"]

        with control.with_remote(r):
            n = nem.node_start_stopper(lambda nodes: nodes[0],
                                       start, stop)
            out = n.invoke(test, {"type": "info", "f": "start"})
            assert out["value"] == {"n1": ["killed", "db"]}
            # double-start refuses
            out = n.invoke(test, {"type": "info", "f": "start"})
            assert "already disrupting" in str(out["value"])
            out = n.invoke(test, {"type": "info", "f": "stop"})
            assert out["value"] == {"n1": ["restarted", "db"]}
            out = n.invoke(test, {"type": "info", "f": "stop"})
            assert out["value"] == "not-started"
        assert calls == [("start", "n1"), ("stop", "n1")]

    def test_hammer_time_signals(self):
        r = dummy.DummyRemote()
        test, _ = make_test(remote=r)
        with control.with_remote(r):
            h = nem.hammer_time("java", targeter=lambda ns: "n2")
            h.invoke(test, {"type": "info", "f": "start"})
            h.invoke(test, {"type": "info", "f": "stop"})
        cmds = [a.get("cmd", "") for h_, _, a in r.log if h_ == "n2"]
        assert any("killall -s STOP java" in c0 for c0 in cmds)
        assert any("killall -s CONT java" in c0 for c0 in cmds)


class TestTruncate:
    def test_truncates_per_plan(self):
        r = dummy.DummyRemote()
        test, _ = make_test(remote=r)
        n = nem.truncate_file()
        n.invoke(test, {"type": "info", "f": "truncate",
                        "value": {"n2": {"file": "/var/db/wal",
                                         "drop": 64}}})
        cmds = [a.get("cmd", "") for h, _, a in r.log if h == "n2"]
        assert any("truncate -c -s -64 /var/db/wal" in c0 for c0 in cmds)


class TestClockNemesis:
    def test_fs(self):
        assert ntime.clock_nemesis().fs() == \
            {"reset", "strobe", "bump", "check-offsets"}

    def test_bump_invokes_tool(self):
        r = dummy.DummyRemote(responses={
            r"bump-time": "1700000000.000000\n",
            r"date \+": "1700000000.5\n"})
        test, _ = make_test(remote=r)
        out = ntime.clock_nemesis().invoke(
            test, {"type": "info", "f": "bump", "value": {"n1": 4000}})
        assert "clock-offsets" in out
        assert set(out["clock-offsets"]) == {"n1"}
        cmds = [a.get("cmd", "") for h, _, a in r.log if h == "n1"]
        assert any("/opt/jepsen/bump-time 4000" in c0 for c0 in cmds)

    def test_check_offsets_all_nodes(self):
        r = dummy.DummyRemote(responses={r"date \+": "123.0\n"})
        test, _ = make_test(remote=r)
        out = ntime.clock_nemesis().invoke(
            test, {"type": "info", "f": "check-offsets"})
        assert set(out["clock-offsets"]) == set(NODES)

    def test_gen_shapes(self):
        rng = random.Random(1)
        test = {"nodes": NODES}
        op = ntime.bump_gen(test, None)
        assert op["f"] == "bump"
        for node, ms in op["value"].items():
            assert node in NODES
            assert 4 <= abs(ms) <= 2 ** 18
        op = ntime.strobe_gen(test, None)
        for node, spec in op["value"].items():
            assert 4 <= spec["delta"] <= 2 ** 18
            assert 1 <= spec["period"] <= 2 ** 10
            assert 0 <= spec["duration"] <= 32

    def test_exp_ms_range(self):
        rng = random.Random(3)
        for _ in range(200):
            v = abs(ntime._exp_ms(rng))
            assert 4 <= v <= 2 ** 18


class TestNativeTools:
    """Local compile/behavior checks for the C++ clock tools (usage
    paths only — actually setting clocks needs root + real clocks)."""

    @pytest.fixture(scope="class")
    def bins(self, tmp_path_factory):
        import shutil
        import subprocess

        if shutil.which("g++") is None:
            pytest.skip("no g++")
        d = tmp_path_factory.mktemp("native")
        src = ntime.NATIVE_DIR
        for b, s in [("bump_time", "bump_time.cpp"),
                     ("strobe_time", "strobe_time.cpp"),
                     ("strobe_time_experiment",
                      "strobe_time_experiment.cpp"),
                     ("adj_time", "adj_time.cpp")]:
            subprocess.run(["g++", "-O2", "-std=c++17", "-o",
                            str(d / b), f"{src}/{s}"], check=True)
        return d

    def test_usage_exits_nonzero(self, bins):
        import subprocess

        for b in ("bump_time", "strobe_time",
                  "strobe_time_experiment", "adj_time"):
            p = subprocess.run([str(bins / b)], capture_output=True)
            assert p.returncode == 1
            assert b"usage" in p.stderr

    def test_strobe_zero_duration_restores(self, bins):
        import subprocess

        # duration 0: loop body never runs; tool restores clock (a no-op
        # settimeofday) and prints 0 flips. Without root, settimeofday
        # fails with exit 2 — either outcome proves arg parsing + flow.
        p = subprocess.run([str(bins / "strobe_time"), "10", "5", "0"],
                           capture_output=True)
        assert p.returncode in (0, 2)
        if p.returncode == 0:
            assert p.stdout.strip() == b"0"

    def test_strobe_experiment_zero_duration_restores(self, bins):
        import subprocess

        # phase-locked variant: same zero-duration contract
        p = subprocess.run(
            [str(bins / "strobe_time_experiment"), "10", "5", "0"],
            capture_output=True)
        assert p.returncode in (0, 2)
        if p.returncode == 0:
            assert p.stdout.strip() == b"0"
