from jepsen_tpu import models as m


def op(f, value=None):
    return {"f": f, "value": value}


def test_cas_register():
    r = m.cas_register()
    r = r.step(op("write", 3))
    assert r == m.CASRegister(3)
    assert r.step(op("read", 3)) == r
    assert r.step(op("read", None)) == r
    assert m.is_inconsistent(r.step(op("read", 4)))
    r2 = r.step(op("cas", (3, 5)))
    assert r2 == m.CASRegister(5)
    assert m.is_inconsistent(r.step(op("cas", (4, 5))))


def test_register():
    r = m.register(1)
    assert m.is_inconsistent(r.step(op("read", 2)))
    assert r.step(op("write", 2)).step(op("read", 2)) == m.Register(2)


def test_mutex():
    x = m.mutex()
    held = x.step(op("acquire"))
    assert held == m.Mutex(True)
    assert m.is_inconsistent(held.step(op("acquire")))
    assert held.step(op("release")) == m.Mutex(False)
    assert m.is_inconsistent(x.step(op("release")))


def test_unordered_queue():
    q = m.unordered_queue()
    q = q.step(op("enqueue", 1)).step(op("enqueue", 2))
    q2 = q.step(op("dequeue", 2))
    assert not m.is_inconsistent(q2)
    assert m.is_inconsistent(q2.step(op("dequeue", 2)))
    # duplicates allowed
    q3 = m.unordered_queue().step(op("enqueue", 7)).step(op("enqueue", 7))
    q3 = q3.step(op("dequeue", 7)).step(op("dequeue", 7))
    assert not m.is_inconsistent(q3)


def test_fifo_queue():
    q = m.fifo_queue().step(op("enqueue", 1)).step(op("enqueue", 2))
    assert m.is_inconsistent(q.step(op("dequeue", 2)))
    q = q.step(op("dequeue", 1))
    assert q == m.FIFOQueue((2,))


def test_device_step_register_matches_model():
    from jepsen_tpu.history import F_CAS, F_READ, F_WRITE, NIL
    # write
    ok, s = m.device_step_register(NIL, F_WRITE, 5, NIL, cas=True)
    assert ok and s == 5
    # read match/mismatch/nil
    assert m.device_step_register(5, F_READ, 5, NIL, True)[0]
    assert not m.device_step_register(5, F_READ, 6, NIL, True)[0]
    assert m.device_step_register(5, F_READ, NIL, NIL, True)[0]
    # cas
    ok, s = m.device_step_register(5, F_CAS, 5, 9, True)
    assert ok and s == 9
    ok, _ = m.device_step_register(5, F_CAS, 4, 9, True)
    assert not ok


def test_device_state():
    assert m.cas_register(4).device_state() == 4
    assert m.cas_register().device_state() == -1
    assert m.mutex().device_state() == 0
