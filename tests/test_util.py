import time

import pytest

from jepsen_tpu import util


def test_real_pmap():
    assert util.real_pmap(lambda x: x * 2, [1, 2, 3]) == [2, 4, 6]


def test_real_pmap_propagates_crash():
    def boom(x):
        if x == 2:
            raise ValueError("boom")
        return x
    with pytest.raises(ValueError):
        util.real_pmap(boom, [1, 2, 3])


def test_bounded_pmap_order():
    assert util.bounded_pmap(lambda x: -x, range(10), max_workers=3) \
        == [-x for x in range(10)]


def test_relative_time():
    with util.relative_time():
        a = util.relative_time_nanos()
        b = util.relative_time_nanos()
        assert 0 <= a <= b
    with pytest.raises(RuntimeError):
        util.relative_time_nanos()


def test_timeout():
    assert util.timeout(5, lambda: 42) == 42
    assert util.timeout(0.05, lambda: time.sleep(1), default="late") == "late"
    with pytest.raises(util.Timeout):
        util.timeout(0.05, lambda: time.sleep(1))


def test_await_fn():
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("not yet")
        return "done"

    assert util.await_fn(flaky, retry_interval=0.01, timeout_secs=5) == "done"
    with pytest.raises(util.Timeout):
        util.await_fn(lambda: 1 / 0, retry_interval=0.01, timeout_secs=0.05)


def test_integer_interval_set_str():
    assert util.integer_interval_set_str([1, 3, 4, 5, 7]) == "#{1 3-5 7}"
    assert util.integer_interval_set_str([]) == "#{}"
    assert util.integer_interval_set_str([1, 2]) == "#{1 2}"


def test_nemesis_intervals():
    hist = [
        {"process": "nemesis", "type": "info", "f": "start-partition",
         "value": None, "time": 1},
        {"process": 0, "type": "invoke", "f": "read", "value": None,
         "time": 2},
        {"process": "nemesis", "type": "info", "f": "stop-partition",
         "value": None, "time": 3},
        {"process": "nemesis", "type": "info", "f": "start-kill",
         "value": None, "time": 4},
    ]
    ivals = util.nemesis_intervals(hist)
    assert len(ivals) == 2
    assert ivals[0][0]["f"] == "start-partition"
    assert ivals[0][1]["f"] == "stop-partition"
    assert ivals[1] == (hist[3], None)


def test_history_latencies():
    hist = [
        {"process": 0, "type": "invoke", "f": "read", "value": None,
         "time": 100},
        {"process": 0, "type": "ok", "f": "read", "value": 1, "time": 350},
    ]
    lats = util.history_latencies(hist)
    assert len(lats) == 1 and lats[0]["latency"] == 250


def test_majority_and_quantile():
    assert util.majority(5) == 3
    assert util.majority(4) == 3
    assert util.quantile([1, 2, 3, 4], 0.5) == 2
    assert util.quantile([1, 2, 3, 4], 1.0) == 4
