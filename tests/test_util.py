import time

import pytest

from jepsen_tpu import util


def test_real_pmap():
    assert util.real_pmap(lambda x: x * 2, [1, 2, 3]) == [2, 4, 6]


def test_real_pmap_propagates_crash():
    def boom(x):
        if x == 2:
            raise ValueError("boom")
        return x
    with pytest.raises(ValueError):
        util.real_pmap(boom, [1, 2, 3])


def test_bounded_pmap_order():
    assert util.bounded_pmap(lambda x: -x, range(10), max_workers=3) \
        == [-x for x in range(10)]


def test_relative_time():
    with util.relative_time():
        a = util.relative_time_nanos()
        b = util.relative_time_nanos()
        assert 0 <= a <= b
    with pytest.raises(RuntimeError):
        util.relative_time_nanos()


def test_timeout():
    assert util.timeout(5, lambda: 42) == 42
    assert util.timeout(0.05, lambda: time.sleep(1), default="late") == "late"
    with pytest.raises(util.Timeout):
        util.timeout(0.05, lambda: time.sleep(1))


def test_timeout_sentinel_and_late_return_discarded():
    """TIMED_OUT is distinct from anything fn could return, and the
    abandoned worker's late return value is discarded — never delivered
    to any caller (Python threads can't be interrupted; the fn runs to
    completion in the background)."""
    import threading
    done = threading.Event()

    def late():
        time.sleep(0.2)
        done.set()
        return "late-value"

    r = util.timeout(0.05, late, default=util.TIMED_OUT)
    assert r is util.TIMED_OUT
    assert not util.TIMED_OUT  # falsy, so `if not result:` guards work
    assert done.wait(5), "abandoned fn still runs to completion"
    assert r is util.TIMED_OUT


def test_timeout_late_exception_discarded():
    def late_boom():
        time.sleep(0.1)
        raise RuntimeError("too late")

    assert util.timeout(0.02, late_boom,
                        default=util.TIMED_OUT) is util.TIMED_OUT
    # the late exception must not surface anywhere
    time.sleep(0.2)


def test_await_fn():
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("not yet")
        return "done"

    assert util.await_fn(flaky, retry_interval=0.01, timeout_secs=5) == "done"
    with pytest.raises(util.Timeout):
        util.await_fn(lambda: 1 / 0, retry_interval=0.01, timeout_secs=0.05)


def test_integer_interval_set_str():
    assert util.integer_interval_set_str([1, 3, 4, 5, 7]) == "#{1 3..5 7}"
    assert util.integer_interval_set_str([]) == "#{}"
    assert util.integer_interval_set_str([1, 2]) == "#{1..2}"
    assert util.integer_interval_set_str([-5, -4, -3]) == "#{-5..-3}"


def nem(f, time):
    return {"process": "nemesis", "type": "info", "f": f, "value": None,
            "time": time}


def test_nemesis_intervals():
    # nemesis ops arrive in invoke/complete pairs; one stop closes all
    # open starts (reference util.clj:745-750 example)
    hist = [nem("start", 1), nem("start", 2),    # pair 1 (s1)
            nem("start", 3), nem("start", 4),    # pair 2 (s2)
            nem("stop", 5), nem("stop", 6)]      # stop pair
    ivals = util.nemesis_intervals(hist)
    assert [(a["time"], b["time"] if b else None) for a, b in ivals] == \
        [(1, 5), (2, 6), (3, 5), (4, 6)]


def test_nemesis_intervals_unclosed():
    hist = [nem("start", 1), nem("start", 2)]
    ivals = util.nemesis_intervals(hist)
    assert ivals == [(hist[0], None), (hist[1], None)]


def test_nemesis_intervals_custom_fs():
    hist = [nem("start-partition", 1), nem("start-partition", 2),
            nem("stop-partition", 3), nem("stop-partition", 4)]
    ivals = util.nemesis_intervals(hist, {"start-partition"},
                                   {"stop-partition"})
    assert [(a["time"], b["time"]) for a, b in ivals] == [(1, 3), (2, 4)]


def test_history_latencies():
    hist = [
        {"process": 0, "type": "invoke", "f": "read", "value": None,
         "time": 100},
        {"process": 1, "type": "invoke", "f": "write", "value": 2,
         "time": 150},
        {"process": 0, "type": "ok", "f": "read", "value": 1, "time": 350},
    ]
    out = util.history_latencies(hist)
    assert len(out) == 3                       # full history preserved
    assert out[0]["latency"] == 250            # invocation annotated
    assert out[0]["completion"]["type"] == "ok"
    assert out[2]["latency"] == 250            # completion annotated
    assert "latency" not in out[1]             # pending invoke untouched


def test_relative_time_nesting():
    with util.relative_time():
        with util.relative_time():
            util.relative_time_nanos()
        # inner exit must restore the outer origin
        assert util.relative_time_nanos() >= 0


def test_relative_time_interleaved_exits_do_not_leak():
    """Concurrent runs (e.g. several tests feeding one verification
    service) interleave enter/exit; the earlier-entered context
    exiting first must not re-install its saved state over the
    still-running sibling — and once BOTH have exited, no origin may
    remain (the old save/restore slot leaked the first context's
    origin here, so code outside any run silently got timestamps)."""
    a = util.relative_time()
    b = util.relative_time()
    a.__enter__()
    b.__enter__()
    a.__exit__(None, None, None)      # a exits while b still runs
    assert util.relative_time_nanos() >= 0   # b's origin still active
    b.__exit__(None, None, None)
    with pytest.raises(RuntimeError):
        util.relative_time_nanos()


def test_majority_and_quantile():
    assert util.majority(5) == 3
    assert util.majority(4) == 3
    assert util.quantile([1, 2, 3, 4], 0.5) == 2
    assert util.quantile([1, 2, 3, 4], 1.0) == 4
