"""Adversarial golden corpus for the Elle cycle-classification stack.

The composite classifier rests on three mechanisms (kernels.py): the
dense distinct-rw-sources G2 test, the budgeted simple-path host
probes, and the oversized-SCC path. Each case here is built to fool
one of them; the expected labels follow Elle's anomaly semantics
(`tests/cycle/wr.clj:31-45`: G-single = a cycle with exactly one
anti-dependency edge, G2-item = a *simple* cycle with two or more).
"""

import numpy as np
import pytest

from jepsen_tpu.checker.elle import kernels
from jepsen_tpu.checker import synth
from jepsen_tpu.checker.elle import list_append


def analyze(n, edge_list, **kw):
    edges = {}
    for i, j, t in edge_list:
        edges.setdefault((i, j), set()).add(t)
    return kernels.analyze_edges(n, edges, **kw)


def flags(out):
    return {k: out[k] for k in ("G0", "G1c", "G-single", "G2-item")}


# -- figure-eights: the distinct-rw-sources test's blind spot ---------------

def test_figure_eight_is_g_single_not_g2():
    """Two one-rw cycles sharing a node have two rw edges with distinct
    sources, but no SIMPLE cycle contains both — G-single, not G2."""
    out = analyze(3, [(0, 1, "rw"), (1, 0, "ww"),
                      (1, 2, "rw"), (2, 1, "ww")])
    assert flags(out) == {"G0": False, "G1c": False,
                          "G-single": True, "G2-item": False}


def test_figure_eight_with_wr_return_paths():
    out = analyze(4, [(0, 1, "rw"), (1, 2, "wr"), (2, 0, "ww"),
                      (2, 3, "rw"), (3, 2, "ww")])
    assert out["G-single"] is True
    assert out["G2-item"] is False


def test_three_petal_flower_shared_center():
    """Many G-single cycles through one shared center node."""
    edges = []
    for k in (1, 2, 3):
        edges.append((0, k, "rw"))
        edges.append((k, 0, "ww"))
    out = analyze(4, edges)
    assert flags(out) == {"G0": False, "G1c": False,
                          "G-single": True, "G2-item": False}


def test_chained_figure_eights():
    """A ladder of single-rw cycles, each sharing a node with the
    next: still no simple two-rw cycle."""
    edges = []
    for k in range(5):
        a, b = k, k + 1
        edges.append((a, b, "rw"))
        edges.append((b, a, "ww"))
    out = analyze(6, edges)
    assert out["G-single"] is True
    assert out["G2-item"] is False


# -- true G2 cycles ----------------------------------------------------------

def test_two_rw_simple_cycle_is_g2():
    out = analyze(4, [(0, 1, "rw"), (1, 2, "ww"),
                      (2, 3, "rw"), (3, 0, "ww")])
    assert flags(out) == {"G0": False, "G1c": False,
                          "G-single": False, "G2-item": True}


def test_g2_cycle_with_attached_g_single_petal():
    """A genuine two-rw simple cycle sharing a node with a one-rw
    cycle: both labels must appear."""
    out = analyze(5, [(0, 1, "rw"), (1, 2, "ww"),
                      (2, 3, "rw"), (3, 0, "ww"),
                      (0, 4, "rw"), (4, 0, "wr")])
    assert out["G-single"] is True
    assert out["G2-item"] is True


def test_adjacent_double_rw_cycle():
    """rw edges may be adjacent in a G2 cycle (write skew shape)."""
    out = analyze(2, [(0, 1, "rw"), (1, 0, "rw")])
    assert out["G-single"] is False
    assert out["G2-item"] is True


# -- G0 / G1c hierarchy ------------------------------------------------------

def test_ww_cycle_is_g0():
    out = analyze(2, [(0, 1, "ww"), (1, 0, "ww")])
    assert out["G0"] is True and out["G1c"] is True
    assert out["G-single"] is False and out["G2-item"] is False


def test_wr_cycle_is_g1c_not_g0():
    out = analyze(2, [(0, 1, "wr"), (1, 0, "ww")])
    assert out["G0"] is False and out["G1c"] is True


def test_g1c_with_unrelated_g_single():
    out = analyze(5, [(0, 1, "wr"), (1, 0, "ww"),
                      (2, 3, "rw"), (3, 2, "ww")])
    assert out["G0"] is False and out["G1c"] is True
    assert out["G-single"] is True and out["G2-item"] is False


# -- oversized-SCC path (force it with a tiny max_dense) --------------------

def _ring(n, rw_at=()):
    return [(k, (k + 1) % n, "rw" if k in rw_at else "ww")
            for k in range(n)]


def test_oversized_ww_ring():
    out = analyze(64, _ring(64), max_dense=8)
    assert out["oversized-sccs"] == 1
    assert out["G0"] is True
    assert out["G-single"] is False and out["G2-item"] is False


def test_oversized_one_rw_ring_is_g_single():
    out = analyze(64, _ring(64, rw_at={10}), max_dense=8)
    assert out["oversized-sccs"] == 1
    assert flags(out) == {"G0": False, "G1c": False,
                          "G-single": True, "G2-item": False}


def test_oversized_two_rw_ring_is_g2():
    out = analyze(64, _ring(64, rw_at={10, 40}), max_dense=8)
    assert out["oversized-sccs"] == 1
    assert out["G-single"] is False
    assert out["G2-item"] is True


def test_oversized_figure_eight_stays_g_single():
    """Two 32-node one-rw rings sharing node 0, classified through the
    oversized path: the probes must not mislabel it G2."""
    edges = []
    for k in range(32):
        edges.append((k, (k + 1) % 32, "rw" if k == 5 else "ww"))
    # second ring on nodes {0, 32..62}
    ring2 = [0] + list(range(32, 63))
    for ix, v in enumerate(ring2):
        w = ring2[(ix + 1) % len(ring2)]
        edges.append((v, w, "rw" if ix == 7 else "ww"))
    out = analyze(63, edges, max_dense=8)
    assert out["oversized-sccs"] == 1
    assert out["G-single"] is True
    assert out["G2-item"] is False


# -- dense kernel vs oversized probes must agree ----------------------------

@pytest.mark.parametrize("seed", range(12))
def test_dense_and_probe_paths_agree_on_random_graphs(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(4, 14))
    m = int(rng.integers(n, 3 * n))
    edge_list = []
    for _ in range(m):
        i, j = rng.integers(0, n, 2)
        if i == j:
            continue
        t = ("ww", "wr", "rw")[int(rng.integers(0, 3))]
        edge_list.append((int(i), int(j), t))
    dense = flags(analyze(n, edge_list, max_dense=4096))
    probed = flags(analyze(n, edge_list, max_dense=2))
    assert dense == probed, (edge_list, dense, probed)


# -- history level -----------------------------------------------------------

def test_injected_g_single_labels_exactly():
    h = synth.append_history(3000, seed=7)
    bad = synth.inject_append_cycles(h, 8, "G-single")
    r = list_append.check(bad)
    assert r["valid?"] is False
    assert "G-single" in r["anomaly-types"]
    assert "G2-item" not in r["anomaly-types"]
    assert "G1c" not in r["anomaly-types"]


def test_injected_mixed_anomalies():
    h = synth.append_history(3000, seed=8)
    bad = synth.inject_append_cycles(h, 4, "G1c")
    bad = synth.inject_append_cycles(bad, 4, "G-single", seed=11,
                                     key_base=2 * 10 ** 9)
    r = list_append.check(bad)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"]
    assert "G-single" in r["anomaly-types"]
