"""TiDB suite tests: DB command generation against the recording dummy
remote, the MySQL wire client against an in-process protocol fake, SQL
client semantics, and complete hermetic suite runs (real wire protocol,
real checkers)."""

import pytest

from fake_mysql import FakeMySQLServer

from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import suite, tidb
from jepsen_tpu.suites.mysql_proto import Conn, MySQLError


@pytest.fixture
def fake():
    f = FakeMySQLServer()
    yield f
    f.stop()


def conn_fn(fake):
    return lambda node: Conn("127.0.0.1", fake.port)


def test_suite_registry():
    assert suite("tidb") is tidb


def test_initial_cluster():
    t = {"nodes": ["n1", "n2"]}
    assert tidb.initial_cluster(t) == \
        "pd1=http://n1:2380,pd2=http://n2:2380"
    assert tidb.pd_endpoints(t) == "n1:2379,n2:2379"


def test_db_setup_commands():
    """Setup installs the tarball and starts pd -> tikv -> tidb in
    order (`db.clj:102-240`)."""
    log = []
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "tidb-v3.0.0-linux-amd64"})
    test = {"nodes": ["n1"], "tarball": "file:///tmp/tidb.tgz"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            tidb.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "pd-server" in cmds and "tikv-server" in cmds \
        and "tidb-server" in cmds
    assert cmds.index("pd-server") < cmds.index("tikv-server") \
        < cmds.index("tidb-server")
    assert "--initial-cluster pd1=http://n1:2380" in cmds


def test_mysql_client_roundtrip(fake):
    c = Conn("127.0.0.1", fake.port)
    c.query("create table if not exists t "
            "(id int not null primary key, sk int not null, val text)")
    assert c.query("insert into t (id, sk, val) values (1, 1, '5')") \
        == (1, None)
    rows, cols = c.query("select val from t where id = 1")
    assert rows == [["5"]] and cols == ["val"]
    with pytest.raises(MySQLError) as ei:
        c.query("insert into t (id, sk, val) values (1, 1, 'x')")
    assert ei.value.code == 1062
    assert c.ping()
    c.close()


def test_txn_client_append_and_read(fake):
    t = {"sql-conn-fn": conn_fn(fake)}
    c = tidb.TxnClient().open(t, "n1")
    c.setup(t)
    op = {"type": "invoke", "f": "txn", "process": 0,
          "value": [["append", 5, 1], ["r", 5, None]]}
    r = c.invoke(t, op)
    assert r["type"] == "ok"
    assert r["value"] == [["append", 5, 1], ["r", 5, [1]]]
    r2 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                      "value": [["append", 5, 2], ["r", 5, None]]})
    assert r2["value"][1] == ["r", 5, [1, 2]]
    # single-mop txns skip begin/commit (txn.clj:66-72)
    r3 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                      "value": [["r", 5, None]]})
    assert r3["value"] == [["r", 5, [1, 2]]]
    c.close(t)


def test_wr_client_reads_ints(fake):
    t = {"sql-conn-fn": conn_fn(fake)}
    c = tidb.WrTxnClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                     "value": [["w", 3, 7], ["r", 3, None]]})
    assert r["type"] == "ok"
    assert r["value"] == [["w", 3, 7], ["r", 3, 7]]
    r2 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                      "value": [["r", 99, None]]})
    assert r2["value"] == [["r", 99, None]]
    c.close(t)


def test_txn_conflict_classified_as_fail(fake):
    # deadlock error (1213) mid-transaction -> definite fail
    fake.fail_hook = lambda sql: (1213, "Deadlock found") \
        if "insert" in sql.lower() else None
    t = {"sql-conn-fn": conn_fn(fake)}
    c = tidb.TxnClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                     "value": [["append", 1, 1], ["r", 1, None]]})
    assert r["type"] == "fail"
    assert r["error"][1] == 1213


def test_unknown_error_mid_write_is_info(fake):
    fake.fail_hook = lambda sql: (1105, "unknown") \
        if "insert" in sql.lower() else None
    t = {"sql-conn-fn": conn_fn(fake)}
    c = tidb.TxnClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                     "value": [["append", 1, 1], ["r", 1, None]]})
    assert r["type"] == "info"
    # but a read-only txn with the same failure is a safe fail
    fake.fail_hook = lambda sql: (1105, "unknown") \
        if "select" in sql.lower() else None
    r2 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                      "value": [["r", 1, None], ["r", 2, None]]})
    assert r2["type"] == "fail"


def test_bank_client(fake):
    t = {"sql-conn-fn": conn_fn(fake), "accounts": [0, 1, 2],
         "total-amount": 30}
    c = tidb.BankClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "read", "process": 0})
    assert r["type"] == "ok" and sum(r["value"].values()) == 30
    xfer = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                        "value": {"from": 0, "to": 1, "amount": 10}})
    assert xfer["type"] == "ok"
    r2 = c.invoke(t, {"type": "invoke", "f": "read", "process": 0})
    assert r2["value"][1] == 10 and sum(r2["value"].values()) == 30
    # overdraw fails cleanly
    bad = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                       "value": {"from": 2, "to": 0, "amount": 99}})
    assert bad["type"] == "fail"


def test_register_client_cas(fake):
    from jepsen_tpu.independent import ktuple
    t = {"sql-conn-fn": conn_fn(fake)}
    c = tidb.RegisterClient().open(t, "n1")
    c.setup(t)
    w = c.invoke(t, {"type": "invoke", "f": "write", "process": 0,
                     "value": ktuple(1, 5)})
    assert w["type"] == "ok"
    r = c.invoke(t, {"type": "invoke", "f": "read", "process": 0,
                     "value": ktuple(1, None)})
    assert r["type"] == "ok" and r["value"].value == 5
    ok = c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                      "value": ktuple(1, (5, 6))})
    assert ok["type"] == "ok"
    no = c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                      "value": ktuple(1, (5, 7))})
    assert no["type"] == "fail"


def test_tidb_test_map_builds():
    t = tidb.tidb_test({"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                        "ssh": {"dummy": True}, "workload": "append",
                        "time-limit": 5, "faults": ["none"]})
    assert t["name"] == "tidb-append"
    assert t["generator"] is not None


@pytest.mark.parametrize("workload", sorted(tidb.WORKLOADS))
def test_hermetic_suite_run(tmp_path, fake, workload):
    """The whole suite end to end: dummy remote for the cluster, fake
    MySQL-protocol TiDB for the data plane, full checker stack. The
    fake is serializable, so every workload must verify."""
    opts = {
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 6,
        "ssh": {"dummy": True},
        "workload": workload,
        "rate": 500,
        # 2s (was 3): the menu grew to 9 workloads (monotonic /
        # sequential / table), so each run gets a slightly tighter
        # budget to keep the file's wall time flat; at rate 500 a 2s
        # run still journals ~1k ops, plenty for every checker here
        "time-limit": 2,
        "ops-per-key": 20,
        "faults": ["none"],
        "store-dir": str(tmp_path / "store"),
    }
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = tidb.tidb_test(opts)
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["sql-conn-fn"] = conn_fn(fake)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    assert len(done["history"]) > 10
