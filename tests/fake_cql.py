"""An in-process CQL-binary-protocol (v4) server standing in for
YugaByte's YCQL API: exercises the suite's wire client
(`jepsen_tpu/suites/cql_proto.py`) against real framing, backed by a
tiny linearizable CQL engine (single global lock; BEGIN/END TRANSACTION
batches apply atomically). Supports exactly the statement shapes the
yugabyte suite issues (`jepsen_tpu/suites/yugabyte.py`): CREATE
KEYSPACE/TABLE/INDEX, USE, INSERT (upsert semantics, as in CQL),
SELECT with =, IN and AND in WHERE, UPDATE with counter increments and
IF conditions, and transaction batches.
"""

from __future__ import annotations

import re
import socketserver

from netutil import NodelayHandler
import struct
import threading

OP_ERROR, OP_STARTUP, OP_READY, OP_QUERY, OP_RESULT = (0x00, 0x01, 0x02,
                                                       0x07, 0x08)
T_BIGINT, T_BOOLEAN, T_COUNTER, T_INT, T_VARCHAR = (0x0002, 0x0004,
                                                    0x0005, 0x0009,
                                                    0x000D)

_TYPES = {"int": T_INT, "bigint": T_BIGINT, "counter": T_COUNTER,
          "boolean": T_BOOLEAN, "varchar": T_VARCHAR, "text": T_VARCHAR}


class CQLFault(Exception):
    def __init__(self, code: int, message: str):
        self.code, self.message = code, message
        super().__init__(message)


def _literal(tok: str):
    tok = tok.strip()
    if tok.startswith("'") and tok.endswith("'"):
        return tok[1:-1]
    if tok.lstrip("-").isdigit():
        return int(tok)
    return tok


_ARGSPLIT = re.compile(r",(?=(?:[^']*'[^']*')*[^']*$)")


class Engine:
    """Shared linearizable store."""

    def __init__(self):
        self.tables: dict[str, dict] = {}
        self.lock = threading.RLock()

    # -- DDL -----------------------------------------------------------------

    def _create_table(self, m):
        name, body = m.group(1), m.group(2)
        name = name.split(".")[-1]
        if name in self.tables:
            return None
        cols, types, pk = [], {}, []
        for coldef in re.split(r",(?![^()]*\))", body):
            coldef = coldef.strip()
            if not coldef:
                continue
            mpk = re.match(r"primary key\s*\(([^)]*)\)", coldef, re.I)
            if mpk:
                pk = [c.strip() for c in mpk.group(1).split(",")]
                continue
            parts = coldef.split()
            cname, ctype = parts[0], parts[1].lower()
            cols.append(cname)
            types[cname] = _TYPES.get(ctype, T_VARCHAR)
            if "primary key" in coldef.lower():
                pk = [cname]
        self.tables[name] = {"cols": cols, "types": types,
                             "pk": pk or [cols[0]], "rows": {}}
        return None

    def _table(self, name: str) -> dict:
        name = name.split(".")[-1]
        t = self.tables.get(name)
        if t is None:
            raise CQLFault(0x2200, f"table {name} does not exist")
        return t

    # -- WHERE parsing -------------------------------------------------------

    @staticmethod
    def _predicate(where: str | None):
        if not where:
            return lambda row: True
        clauses = []
        for part in re.split(r"\s+and\s+", where, flags=re.I):
            part = part.strip()
            min_ = re.match(r"(\w+)\s+in\s*\(([^)]*)\)", part, re.I)
            if min_:
                col = min_.group(1)
                vals = {_literal(v) for v in min_.group(2).split(",")}
                clauses.append((col, vals, True))
                continue
            meq = re.match(r"(\w+)\s*=\s*(.+)", part)
            if not meq:
                raise CQLFault(0x2000, f"bad where clause {part!r}")
            clauses.append((meq.group(1), _literal(meq.group(2)), False))

        def pred(row):
            for col, v, is_in in clauses:
                if is_in:
                    if row.get(col) not in v:
                        return False
                elif row.get(col) != v:
                    return False
            return True
        return pred

    # -- statements ----------------------------------------------------------

    def _insert(self, m):
        t = self._table(m.group(1))
        cnames = [c.strip() for c in m.group(2).split(",")]
        values = [_literal(v) for v in _ARGSPLIT.split(m.group(3))]
        row = dict(zip(cnames, values))
        key = tuple(row.get(k) for k in t["pk"])
        if key in t["rows"]:
            t["rows"][key].update(row)   # CQL INSERT is an upsert
        else:
            t["rows"][key] = row
        return None

    def _select(self, m):
        cols, name, where = m.group(1), m.group(2), m.group(3)
        t = self._table(name)
        pred = self._predicate(where)
        rows = [r for r in t["rows"].values() if pred(r)]
        out_cols = t["cols"] if cols.strip() == "*" else \
            [c.strip() for c in cols.split(",")]
        data = [[r.get(c) for c in out_cols] for r in rows]
        types = [t["types"].get(c, T_VARCHAR) for c in out_cols]
        return data, out_cols, types

    def _update(self, m):
        name, assigns, where, cond = (m.group(1), m.group(2), m.group(3),
                                      m.group(4))
        t = self._table(name)
        pred = self._predicate(where)
        hits = [r for r in t["rows"].values() if pred(r)]
        if not hits and not cond:
            # CQL UPDATE on a missing row creates it (counter semantics);
            # synthesize the row from the WHERE equality clauses.
            row = {}
            for part in re.split(r"\s+and\s+", where or "", flags=re.I):
                meq = re.match(r"(\w+)\s*=\s*(.+)", part.strip())
                if meq:
                    row[meq.group(1)] = _literal(meq.group(2))
            key = tuple(row.get(k) for k in t["pk"])
            t["rows"][key] = row
            hits = [row]
        if cond:
            mc = re.match(r"(\w+)\s*=\s*(.+)", cond.strip())
            ccol, cval = mc.group(1), _literal(mc.group(2))
            applied = bool(hits) and all(r.get(ccol) == cval
                                         for r in hits)
            if not applied:
                cur = hits[0].get(ccol) if hits else None
                return ([[False, cur]], ["[applied]", ccol],
                        [T_BOOLEAN, t["types"].get(ccol, T_VARCHAR)])
        for r in hits:
            for assign in _ARGSPLIT.split(assigns):
                col, expr = assign.split("=", 1)
                col, expr = col.strip(), expr.strip()
                marith = re.match(rf"{col}\s*([+-])\s*(\d+)$", expr)
                if marith:
                    base = int(r.get(col) or 0)
                    d = int(marith.group(2))
                    r[col] = base + d if marith.group(1) == "+" \
                        else base - d
                else:
                    r[col] = _literal(expr)
        if cond:
            return [[True]], ["[applied]"], [T_BOOLEAN]
        return None

    _CREATE_RE = re.compile(
        r"create table (?:if not exists )?([\w.]+)\s*\((.*)\)"
        r"\s*(?:with\s+.*)?$", re.I | re.S)
    _INSERT_RE = re.compile(
        r"insert into ([\w.]+)\s*\(([^)]*)\)\s*values\s*\((.*)\)\s*$",
        re.I | re.S)
    _SELECT_RE = re.compile(
        r"select\s+(.*?)\s+from\s+([\w.]+)(?:\s+where\s+(.*?))?\s*$",
        re.I | re.S)
    _UPDATE_RE = re.compile(
        r"update ([\w.]+)\s+set\s+(.*?)(?:\s+where\s+(.*?))?"
        r"(?:\s+if\s+(.*?))?\s*$", re.I | re.S)

    def execute(self, cql: str):
        """Returns None for void results or (rows, cols, types)."""
        cql = cql.strip().rstrip(";").strip()
        low = cql.lower()
        with self.lock:
            if low.startswith("begin transaction"):
                body = re.sub(r"end transaction$", "",
                              re.sub(r"^begin transaction", "", cql,
                                     flags=re.I),
                              flags=re.I)
                for stmt in body.split(";"):
                    if stmt.strip():
                        self.execute(stmt)
                return None
            if low.startswith(("create keyspace", "create index", "use ",
                               "drop index")):
                return None
            m = self._CREATE_RE.match(cql)
            if m:
                return self._create_table(m)
            m = self._INSERT_RE.match(cql)
            if m:
                return self._insert(m)
            m = self._SELECT_RE.match(cql)
            if m:
                return self._select(m)
            m = self._UPDATE_RE.match(cql)
            if m:
                return self._update(m)
            raise CQLFault(0x2000, f"unsupported statement: {cql!r}")


# ---------------------------------------------------------------------------
# wire server
# ---------------------------------------------------------------------------

def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _encode_value(tid: int, v) -> bytes:
    if v is None:
        return struct.pack("!i", -1)
    if tid == T_INT:
        return struct.pack("!ii", 4, int(v))
    if tid in (T_BIGINT, T_COUNTER):
        return struct.pack("!iq", 8, int(v))
    if tid == T_BOOLEAN:
        return struct.pack("!iB", 1, 1 if v else 0)
    b = str(v).encode()
    return struct.pack("!i", len(b)) + b


class _Handler(NodelayHandler):

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def _frame(self, stream: int, opcode: int, body: bytes) -> None:
        self.request.sendall(
            struct.pack("!BBhBI", 0x84, 0x00, stream, opcode, len(body))
            + body)

    def _error(self, stream: int, code: int, msg: str) -> None:
        self._frame(stream, OP_ERROR,
                    struct.pack("!i", code) + _string(msg))

    def _rows(self, stream: int, rows, cols, types) -> None:
        body = struct.pack("!iii", 0x0002, 0x0001, len(cols))
        body += _string("jepsen") + _string("t")
        for c, tid in zip(cols, types):
            body += _string(c) + struct.pack("!H", tid)
        body += struct.pack("!i", len(rows))
        for r in rows:
            for tid, v in zip(types, r):
                body += _encode_value(tid, v)
        self._frame(stream, OP_RESULT, body)

    def handle(self):
        server: FakeCQLServer = self.server.outer   # type: ignore
        while True:
            try:
                hdr = self._recv_exact(9)
            except (ConnectionError, OSError):
                return
            _ver, _flags, stream, opcode, length = struct.unpack(
                "!BBhBI", hdr)
            body = self._recv_exact(length)
            if opcode == OP_STARTUP:
                self._frame(stream, OP_READY, b"")
                continue
            if opcode != OP_QUERY:
                self._error(stream, 0x000A,
                            f"unsupported opcode {opcode}")
                continue
            (qlen,) = struct.unpack("!i", body[:4])
            cql = body[4:4 + qlen].decode()
            hook = server.fail_hook
            if hook:
                fault = hook(cql)
                if fault:
                    code, msg = fault
                    self._error(stream, code, msg)
                    continue
            try:
                res = server.engine.execute(cql)
            except CQLFault as e:
                self._error(stream, e.code, e.message)
                continue
            if res is None:
                self._frame(stream, OP_RESULT,
                            struct.pack("!i", 0x0001))   # void
            else:
                self._rows(stream, *res)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeCQLServer:
    """One fake YCQL endpoint; all connections share the engine.
    `fail_hook(cql) -> (code, message) | None` injects errors."""

    def __init__(self):
        self.engine = Engine()
        self.fail_hook = None
        self._srv = _Server(("127.0.0.1", 0), _Handler)
        self._srv.outer = self
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
