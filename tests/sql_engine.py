"""A tiny in-memory SQL engine backing the fake MySQL/Postgres servers.

Supports exactly the statement shapes the tidb/cockroach suites issue
(create table / insert .. on duplicate key update / upsert / select /
update / begin / commit / rollback), with serializable semantics: a
global lock is held from BEGIN to COMMIT, and ROLLBACK restores the
pre-transaction snapshot. This mirrors the hermetic-fake test tier the
reference gets from `:ssh {:dummy? true}` + in-JVM databases
(`jepsen/src/jepsen/tests.clj:27-67`).
"""

from __future__ import annotations

import copy
import re
import threading


class SQLError(Exception):
    def __init__(self, code, message):
        super().__init__(message)
        self.code = code
        self.message = message


_CREATE = re.compile(
    r"create table (?:if not exists )?(\w+)\s*\((.*)\)"
    r"\s*(?:engine\s*=\s*\w+\s*)?$",
    re.I | re.S)
_INSERT = re.compile(
    r"(insert|upsert) into (\w+)\s*\(([^)]*)\)\s*values\s*\((.*?)\)"
    r"(?:\s+on duplicate key update\s+(.*)"
    r"|\s+on conflict\s*\([^)]*\)\s+do update set\s+(.*))?$",
    re.I | re.S)
_SELECT = re.compile(
    r"select\s+(.*?)\s+from\s+(\w+)(?:\s+where\s+(\w+)\s*=\s*(\S+))?"
    r"(?:\s+for update)?\s*$", re.I | re.S)
_UPDATE = re.compile(
    r"update (\w+)\s+set\s+(.*?)\s+where\s+(\w+)\s*=\s*(\S+)\s*$",
    re.I | re.S)
_CONCAT = re.compile(r"concat\((.*)\)\s*$", re.I)
# split on commas outside single-quoted strings
_ARGSPLIT = re.compile(r",(?=(?:[^']*'[^']*')*[^']*$)")


def _literal(tok: str):
    tok = tok.strip().rstrip(";")
    if tok.startswith("'") and tok.endswith("'"):
        return tok[1:-1]
    if tok.lstrip("-").isdigit():
        return int(tok)
    return tok


class Engine:
    """One shared database; connections are `Session`s."""

    def __init__(self):
        self.tables: dict[str, dict] = {}
        self.lock = threading.RLock()

    def session(self) -> "Session":
        return Session(self)


class Session:
    def __init__(self, engine: Engine):
        self.engine = engine
        self.in_txn = False
        self.snapshot = None

    def execute(self, sql: str):
        """Returns (rows, cols) for selects, (affected, None) else."""
        sql = sql.strip().rstrip(";").strip()
        low = sql.lower()
        if low.startswith("begin") or low.startswith("start transaction"):
            return self._begin()
        if low.startswith("commit"):
            return self._commit()
        if low.startswith("rollback"):
            return self._rollback()
        with self.engine.lock:
            if low.startswith("create index"):
                return 0, None
            if low.startswith("drop table"):
                name = sql.split()[-1]
                self.engine.tables.pop(name, None)
                return 0, None
            m = _CREATE.match(sql)
            if m:
                return self._create(m)
            m = _INSERT.match(sql)
            if m:
                return self._insert(m)
            m = _SELECT.match(sql)
            if m:
                return self._select(m)
            m = _UPDATE.match(sql)
            if m:
                return self._update(m)
            if low.startswith("set "):
                return 0, None
            if low.startswith(("create database", "use ")):
                return 0, None
            raise SQLError(1064, f"unsupported statement: {sql!r}")

    # -- transactions ------------------------------------------------------

    def _begin(self):
        if not self.in_txn:
            self.engine.lock.acquire()
            self.in_txn = True
            self.snapshot = copy.deepcopy(self.engine.tables)
        return 0, None

    def _commit(self):
        if self.in_txn:
            self.in_txn = False
            self.snapshot = None
            self.engine.lock.release()
        return 0, None

    def _rollback(self):
        if self.in_txn:
            self.engine.tables.clear()
            self.engine.tables.update(self.snapshot)
            self.in_txn = False
            self.snapshot = None
            self.engine.lock.release()
        return 0, None

    def abort(self):
        """Connection dropped mid-transaction."""
        self._rollback()

    # -- statements --------------------------------------------------------

    def _create(self, m):
        name, body = m.group(1), m.group(2)
        if name in self.engine.tables:
            return 0, None
        cols, pk, auto, defaults = [], None, None, {}
        for coldef in re.split(r",(?![^()]*\))", body):
            coldef = coldef.strip()
            if not coldef or coldef.lower().startswith(("primary key",
                                                        "index", "unique")):
                inner = re.search(r"\((\w+)\)", coldef)
                if coldef.lower().startswith("primary key") and inner:
                    pk = inner.group(1)
                continue
            cname = coldef.split()[0]
            cols.append(cname)
            if "primary key" in coldef.lower():
                pk = cname
            if "auto_increment" in coldef.lower() or \
                    "serial" in coldef.lower():
                auto = cname
            mdef = re.search(r"default\s+(\S+)", coldef, re.I)
            if mdef:
                defaults[cname] = _literal(mdef.group(1))
        self.engine.tables[name] = {
            "cols": cols, "pk": pk, "auto": auto, "next": 1, "rows": {},
            "seq": 0, "defaults": defaults}
        return 0, None

    def _table(self, name):
        t = self.engine.tables.get(name)
        if t is None:
            raise SQLError(1146, f"table {name!r} doesn't exist")
        return t

    def _insert(self, m):
        verb, name, cols, vals = (m.group(1).lower(), m.group(2),
                                  m.group(3), m.group(4))
        on_dup = m.group(5) or m.group(6)  # mysql / postgres spellings
        t = self._table(name)
        cnames = [c.strip() for c in cols.split(",")]
        values = [_literal(v) for v in _ARGSPLIT.split(vals)]
        row = dict(t.get("defaults") or {})
        row.update(dict(zip(cnames, values)))
        if t["auto"] and t["auto"] not in row:
            row[t["auto"]] = t["next"]
            t["next"] += 1
        pk = t["pk"] or t["auto"]
        key = row.get(pk) if pk else t["seq"]
        t["seq"] += 1
        if pk and key in t["rows"]:
            if verb == "upsert":
                t["rows"][key].update(row)
                return 1, None
            if on_dup:
                existing = t["rows"][key]
                for assign in re.split(r",(?![^()]*\))", on_dup):
                    col, expr = assign.split("=", 1)
                    existing[col.strip()] = self._eval(expr.strip(),
                                                      existing)
                return 2, None
            raise SQLError(1062, f"duplicate entry {key!r} for "
                                 f"primary key of {name!r}")
        t["rows"][key] = row
        return 1, None

    def _eval(self, expr: str, row: dict):
        mc = _CONCAT.match(expr)
        if mc:
            parts = []
            for tok in _ARGSPLIT.split(mc.group(1)):
                tok = tok.strip()
                if re.fullmatch(r"\w+", tok) and not tok.isdigit() \
                        and tok in row:
                    parts.append(str(row.get(tok) or ""))
                else:
                    parts.append(str(_literal(tok)))
            return "".join(parts)
        if expr in row:
            return row[expr]
        return _literal(expr)

    def _select(self, m):
        cols, name, wcol, wval = (m.group(1), m.group(2), m.group(3),
                                  m.group(4))
        t = self._table(name)
        rows = list(t["rows"].values())
        if wcol:
            wv = _literal(wval)
            rows = [r for r in rows if r.get(wcol) == wv]
        if cols.strip() == "*":
            out_cols = t["cols"]
        else:
            out_cols = [c.strip().strip("()") for c in cols.split(",")]
            agg = re.match(r"(max|count)\((\w+|\*)\)", out_cols[0], re.I)
            if agg:
                fn, col = agg.group(1).lower(), agg.group(2)
                if fn == "count":
                    return [[str(len(rows))]], [f"count({col})"]
                vals = [r.get(col) for r in rows if r.get(col) is not None]
                mx = max(vals) if vals else None
                return [[None if mx is None else str(mx)]], [f"max({col})"]
        out = [[None if r.get(c) is None else str(r.get(c))
                for c in out_cols] for r in rows]
        return out, out_cols

    def _update(self, m):
        name, assigns, wcol, wval = (m.group(1), m.group(2), m.group(3),
                                     m.group(4))
        t = self._table(name)
        wv = _literal(wval)
        n = 0
        for r in t["rows"].values():
            if r.get(wcol) == wv:
                # split assignments on commas outside parens, so
                # concat(a, ',', b) survives intact
                for assign in re.split(r",(?![^()]*\))", assigns):
                    col, expr = assign.split("=", 1)
                    col = col.strip()
                    expr = expr.strip()
                    marith = re.match(
                        r"(\w+)\s*([+-])\s*(\d+)$", expr)
                    if marith and marith.group(1) in r:
                        base = int(r[marith.group(1)])
                        d = int(marith.group(3))
                        r[col] = base + d if marith.group(2) == "+" \
                            else base - d
                    else:
                        r[col] = self._eval(expr, r)
                n += 1
        return n, None
