"""A minimal in-process etcd v3 JSON-gateway fake: /v3/kv/range, put,
and txn (VALUE-EQUAL compares) over a lock-guarded dict. Lets the etcd
suite run a complete hermetic test — real HTTP, real client code, no
etcd binary."""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _b64d(s: str) -> str:
    return base64.b64decode(s).decode()


def _b64e(s: str) -> str:
    return base64.b64encode(str(s).encode()).decode()


class FakeEtcd:
    def __init__(self):
        self.kv: dict[str, str] = {}
        self.rev = 1
        self.lock = threading.Lock()
        self.server: ThreadingHTTPServer | None = None

    # kv semantics ---------------------------------------------------------

    def range(self, req: dict) -> dict:
        key = _b64d(req["key"])
        end = _b64d(req["range_end"]) if req.get("range_end") else None
        with self.lock:
            if end is None:
                items = [(key, self.kv[key])] if key in self.kv else []
            else:
                items = sorted((k, v) for k, v in self.kv.items()
                               if key <= k < end)
        return {"header": {"revision": str(self.rev)},
                "kvs": [{"key": _b64e(k), "value": _b64e(v)}
                        for k, v in items],
                "count": str(len(items))}

    def put(self, req: dict) -> dict:
        with self.lock:
            self.kv[_b64d(req["key"])] = _b64d(req["value"])
            self.rev += 1
        return {"header": {"revision": str(self.rev)}}

    def txn(self, req: dict) -> dict:
        with self.lock:
            ok = True
            for cmp in req.get("compare") or []:
                assert cmp.get("target") == "VALUE"
                assert cmp.get("result") == "EQUAL"
                k = _b64d(cmp["key"])
                want = _b64d(cmp["value"])
                if self.kv.get(k) != want:
                    ok = False
            branch = req.get("success" if ok else "failure") or []
            for o in branch:
                if "requestPut" in o:
                    p = o["requestPut"]
                    self.kv[_b64d(p["key"])] = _b64d(p["value"])
                    self.rev += 1
        return {"header": {"revision": str(self.rev)},
                "succeeded": ok}

    # http -----------------------------------------------------------------

    def start(self) -> int:
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                route = {"/v3/kv/range": fake.range,
                         "/v3/kv/put": fake.put,
                         "/v3/kv/txn": fake.txn}.get(self.path)
                if route is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(route(req)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        return self.server.server_address[1]

    def stop(self):
        if self.server:
            self.server.shutdown()
