"""Catalog suites: disque, raftis, rabbitmq, galera, percona, stolon,
postgres-rds — client semantics against wire-protocol fakes, DB command
generation against the recording dummy remote, and hermetic end-to-end
runs through core.run for each suite's signature workload."""

import pytest

from fake_mysql import FakeMySQLServer
from fake_pg import FakePGServer
from fake_rabbitmq import FakeRabbitMQ
from fake_resp import FakeDisque, FakeRedis

import jepsen_tpu.db
import jepsen_tpu.os_
from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import (disque, galera, percona, postgres_rds,
                               rabbitmq, raftis, stolon, suite)
from jepsen_tpu.suites.mysql_proto import Conn as MyConn
from jepsen_tpu.suites.pg_proto import Conn as PgConn
from jepsen_tpu.suites.resp_proto import Conn as RespConn


def test_suite_registry():
    assert suite("disque") is disque
    assert suite("raftis") is raftis
    assert suite("rabbitmq") is rabbitmq
    assert suite("galera") is galera
    assert suite("percona") is percona
    assert suite("stolon") is stolon
    assert suite("postgres-rds") is postgres_rds


def _hermetic(test_map, conn_key, conn_fn, tmp_path):
    test_map["db"] = jepsen_tpu.db.noop
    test_map["os"] = jepsen_tpu.os_.noop
    test_map[conn_key] = conn_fn
    test_map["store-dir"] = str(tmp_path / "store")
    return core.run(test_map)


# -- disque ------------------------------------------------------------------

def test_disque_queue_client():
    f = FakeDisque()
    try:
        t = {"resp-conn-fn": lambda n: RespConn("127.0.0.1", f.port)}
        c = disque.QueueClient().open(t, "n1")
        assert c.invoke(t, {"type": "invoke", "f": "enqueue",
                            "value": 7, "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "dequeue",
                         "value": None, "process": 0})
        assert r["type"] == "ok" and r["value"] == 7
        r2 = c.invoke(t, {"type": "invoke", "f": "dequeue",
                          "value": None, "process": 0})
        assert r2["type"] == "fail"
        c.invoke(t, {"type": "invoke", "f": "enqueue", "value": 8,
                     "process": 0})
        d = c.invoke(t, {"type": "invoke", "f": "drain", "value": None,
                         "process": 0})
        assert d["type"] == "ok" and d["value"] == [8]
        c.close(t)
    finally:
        f.stop()


def test_disque_hermetic_run(tmp_path):
    f = FakeDisque()
    try:
        t = disque.disque_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "rate": 300, "time-limit": 3,
            "faults": ["none"]})
        done = _hermetic(t, "resp-conn-fn",
                         lambda n: RespConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- raftis ------------------------------------------------------------------

def test_raftis_register_client():
    f = FakeRedis()
    try:
        t = {"resp-conn-fn": lambda n: RespConn("127.0.0.1", f.port)}
        c = raftis.RegisterClient().open(t, "n1")
        r0 = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                          "process": 0})
        assert r0["type"] == "ok" and r0["value"] is None
        assert c.invoke(t, {"type": "invoke", "f": "write", "value": 3,
                            "process": 0})["type"] == "ok"
        r1 = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                          "process": 0})
        assert r1["value"] == 3
        c.close(t)
    finally:
        f.stop()


def test_raftis_no_leader_is_definite_fail():
    f = FakeRedis()
    f.fail_hook = lambda args: \
        "write InComplete: no leader node!" if args[0] == "SET" else None
    try:
        t = {"resp-conn-fn": lambda n: RespConn("127.0.0.1", f.port)}
        c = raftis.RegisterClient().open(t, "n1")
        r = c.invoke(t, {"type": "invoke", "f": "write", "value": 1,
                         "process": 0})
        assert r["type"] == "fail"
        c.close(t)
    finally:
        f.stop()


def test_raftis_hermetic_run(tmp_path):
    f = FakeRedis()
    try:
        t = raftis.raftis_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "rate": 100, "time-limit": 3,
            "faults": ["none"]})
        done = _hermetic(t, "resp-conn-fn",
                         lambda n: RespConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- rabbitmq ----------------------------------------------------------------

def test_rabbitmq_queue_client():
    f = FakeRabbitMQ()
    try:
        t = {"mgmt-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = rabbitmq.QueueClient().open(t, "n1")
        c.setup(t)
        assert c.invoke(t, {"type": "invoke", "f": "enqueue",
                            "value": 5, "process": 0})["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "dequeue",
                         "value": None, "process": 0})
        assert r["type"] == "ok" and r["value"] == 5
        assert c.invoke(t, {"type": "invoke", "f": "dequeue",
                            "value": None,
                            "process": 0})["type"] == "fail"
    finally:
        f.stop()


def test_rabbitmq_mutex_client():
    f = FakeRabbitMQ()
    try:
        t = {"mgmt-url-fn": lambda n: f"http://127.0.0.1:{f.port}"}
        c = rabbitmq.MutexClient().open(t, "n1")
        c.setup(t)
        # token seeded once: acquire wins, second acquire fails
        a1 = c.invoke(t, {"type": "invoke", "f": "acquire",
                          "process": 0})
        assert a1["type"] == "ok"
        c2 = rabbitmq.MutexClient().open(t, "n1")
        a2 = c2.invoke(t, {"type": "invoke", "f": "acquire",
                           "process": 1})
        assert a2["type"] == "fail"
        # release without holding mints nothing
        r2 = c2.invoke(t, {"type": "invoke", "f": "release",
                           "process": 1})
        assert r2["type"] == "fail"
        assert c.invoke(t, {"type": "invoke", "f": "release",
                            "process": 0})["type"] == "ok"
        assert c2.invoke(t, {"type": "invoke", "f": "acquire",
                             "process": 1})["type"] == "ok"
    finally:
        f.stop()


@pytest.mark.parametrize("workload", sorted(rabbitmq.WORKLOADS))
def test_rabbitmq_hermetic_run(tmp_path, workload):
    f = FakeRabbitMQ()
    try:
        t = rabbitmq.rabbitmq_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "workload": workload, "rate": 100,
            "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, "mgmt-url-fn",
                         lambda n: f"http://127.0.0.1:{f.port}",
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- galera / percona --------------------------------------------------------

def test_galera_dirty_reads_client_and_checker():
    f = FakeMySQLServer()
    try:
        t = {"sql-conn-fn": lambda n: MyConn("127.0.0.1", f.port)}
        c = galera.DirtyReadsClient(3).open(t, "n1")
        c.setup(t)
        w = c.invoke(t, {"type": "invoke", "f": "write", "value": 42,
                         "process": 0})
        assert w["type"] == "ok"
        r = c.invoke(t, {"type": "invoke", "f": "read", "value": None,
                         "process": 0})
        assert r["type"] == "ok" and r["value"] == [42, 42, 42]
        # checker: a failed write visible in a read is dirty
        from jepsen_tpu.history import history
        h = history([
            {"type": "invoke", "f": "write", "value": 9, "process": 0,
             "time": 0},
            {"type": "fail", "f": "write", "value": 9, "process": 0,
             "time": 1},
            {"type": "invoke", "f": "read", "value": None, "process": 1,
             "time": 2},
            {"type": "ok", "f": "read", "value": [9, 9, 9], "process": 1,
             "time": 3},
        ])
        res = galera.DirtyReadsChecker().check({}, h, {})
        assert res["valid?"] is False and res["dirty-reads"]
    finally:
        f.stop()


def test_percona_shares_galera_workloads():
    assert percona.WORKLOADS is galera.WORKLOADS
    t = percona.percona_test({
        "nodes": ["n1"], "concurrency": 1, "ssh": {"dummy": True},
        "time-limit": 1, "faults": ["none"]})
    assert t["name"] == "percona-dirty-reads"


@pytest.mark.parametrize("workload", sorted(galera.WORKLOADS))
def test_galera_hermetic_run(tmp_path, workload):
    f = FakeMySQLServer()
    try:
        t = galera.galera_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "workload": workload, "rate": 100,
            "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, "sql-conn-fn",
                         lambda n: MyConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- stolon / postgres-rds ---------------------------------------------------

def test_stolon_append_client():
    f = FakePGServer()
    try:
        t = {"sql-conn-fn": lambda n: PgConn("127.0.0.1", f.port)}
        c = stolon.AppendClient().open(t, "n1")
        c.setup(t)
        r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                         "value": [["append", 1, 1], ["r", 1, None]]})
        assert r["type"] == "ok"
        assert r["value"] == [["append", 1, 1], ["r", 1, [1]]]
        r2 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                          "value": [["append", 1, 2], ["r", 1, None]]})
        assert r2["value"][1] == ["r", 1, [1, 2]]
    finally:
        f.stop()


def test_stolon_db_commands():
    log = []
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "stolon-v0.16.0-linux-amd64"})
    test = {"nodes": ["n1", "n2"], "tarball": "file:///tmp/stolon.tgz"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            stolon.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "stolonctl init" in cmds          # first node inits
    assert "stolon-sentinel" in cmds and "stolon-keeper" in cmds \
        and "stolon-proxy" in cmds
    assert "--store-endpoints http://n1:2379,http://n2:2379" in cmds


@pytest.mark.parametrize("workload", sorted(stolon.WORKLOADS))
def test_stolon_hermetic_run(tmp_path, workload):
    f = FakePGServer()
    try:
        # accounts 0-3 and rate 300: enough transfer attempts that at
        # least one lands on a funded account even on a slow loaded
        # run — with 8 accounts and ~15 ops, all transfers can
        # legitimately fail (insufficient funds) and the stats checker
        # correctly flags an op type with zero oks
        t = stolon.stolon_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 3,
            "ssh": {"dummy": True}, "workload": workload, "rate": 300,
            "accounts": [0, 1, 2, 3],
            "time-limit": 3, "faults": ["none"]})
        done = _hermetic(t, "sql-conn-fn",
                         lambda n: PgConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


def test_postgres_rds_hermetic_run(tmp_path):
    f = FakePGServer()
    try:
        # rate/time sized so even a load-starved run lands ok ops of
        # every f (the stats checker demands one ok per f; this test
        # flaked rarely under full-suite machine load)
        t = postgres_rds.postgres_rds_test({
            "nodes": ["n1"], "concurrency": 3, "ssh": {"dummy": True},
            "rate": 300, "time-limit": 4})
        done = _hermetic(t, "sql-conn-fn",
                         lambda n: PgConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()


# -- mongodb -----------------------------------------------------------------

def test_mongodb_document_cas_client():
    from fake_mongo import FakeMongo
    from jepsen_tpu.suites import mongodb
    from jepsen_tpu.suites.bson_proto import Conn as MongoConn
    from jepsen_tpu.independent import ktuple

    f = FakeMongo()
    try:
        t = {"mongo-conn-fn": lambda n: MongoConn("127.0.0.1", f.port)}
        c = mongodb.DocumentCASClient().open(t, "n1")
        r0 = c.invoke(t, {"type": "invoke", "f": "read", "process": 0,
                          "value": ktuple(1, None)})
        assert r0["type"] == "ok" and r0["value"].value is None
        w = c.invoke(t, {"type": "invoke", "f": "write", "process": 0,
                         "value": ktuple(1, 5)})
        assert w["type"] == "ok"
        r1 = c.invoke(t, {"type": "invoke", "f": "read", "process": 0,
                          "value": ktuple(1, None)})
        assert r1["value"].value == 5
        ok = c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                          "value": ktuple(1, (5, 6))})
        assert ok["type"] == "ok"
        no = c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                          "value": ktuple(1, (5, 7))})
        assert no["type"] == "fail"
        c.close(t)
    finally:
        f.stop()


def test_mongodb_error_classification():
    from fake_mongo import FakeMongo
    from jepsen_tpu.suites import mongodb
    from jepsen_tpu.suites.bson_proto import Conn as MongoConn
    from jepsen_tpu.independent import ktuple

    f = FakeMongo()
    f.fail_hook = lambda cmd: (10107, "not primary") \
        if "update" in cmd else None
    try:
        t = {"mongo-conn-fn": lambda n: MongoConn("127.0.0.1", f.port)}
        c = mongodb.DocumentCASClient().open(t, "n1")
        w = c.invoke(t, {"type": "invoke", "f": "write", "process": 0,
                         "value": ktuple(1, 5)})
        assert w["type"] == "fail"  # NotWritablePrimary: never applied
        f.fail_hook = lambda cmd: (9001, "mystery") \
            if "update" in cmd else None
        w2 = c.invoke(t, {"type": "invoke", "f": "write", "process": 0,
                          "value": ktuple(1, 5)})
        assert w2["type"] == "info"  # unknown error: indeterminate
        c.close(t)
    finally:
        f.stop()


@pytest.mark.parametrize("workload", ["register", "set"])
def test_mongodb_hermetic_run(tmp_path, workload):
    from fake_mongo import FakeMongo
    from jepsen_tpu.suites import mongodb
    from jepsen_tpu.suites.bson_proto import Conn as MongoConn

    f = FakeMongo()
    try:
        t = mongodb.mongodb_test({
            "nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "ssh": {"dummy": True}, "workload": workload, "rate": 200,
            "time-limit": 3, "ops-per-key": 20, "faults": ["none"]})
        done = _hermetic(t, "mongo-conn-fn",
                         lambda n: MongoConn("127.0.0.1", f.port),
                         tmp_path)
        assert done["results"]["valid?"] is True, done["results"]
    finally:
        f.stop()
