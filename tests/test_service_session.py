"""The session-resilient wire protocol (ISSUE 17): monotonic per-op
sequence numbers with server-side replay dedup, client re-attach with
unacked-op replay across injected socket drops, and the hardened
acceptor (malformed / oversized / split / truncated frames answer with
an error instead of killing the connection thread).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time

import pytest

from jepsen_tpu import models, service, store
from jepsen_tpu.checker import streaming, synth

MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8
FRONTIER = 128
CKPT = 2
TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    from jepsen_tpu import _platform
    _platform.reset_fault_injection()
    yield
    _platform.reset_fault_injection()


def _canon(x):
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def _strip(d, extra=()):
    return _canon({k: v for k, v in d.items()
                   if k not in TIMING + tuple(extra)})


def _jops(h):
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


def _solo(ops, **kw):
    s = streaming.WglStream(MODEL, chunk_entries=CHUNK, slots=SLOTS,
                            frontier=FRONTIER, checkpoint_every=CKPT,
                            **kw)
    for op in ops:
        s.feed(op)
    return s.finish()


_HISTS: dict = {}


def _hist(seed, n=300):
    if seed not in _HISTS:
        h = synth.register_history(n, concurrency=3, values=5,
                                   seed=seed)
        ops = _jops(h)
        _HISTS[seed] = (ops, _solo(ops))
    return _HISTS[seed]


def _wgl_spec(**over):
    sp = {"kind": "wgl", "model": service.model_spec(MODEL),
          "chunk-entries": CHUNK, "slots": SLOTS, "engine": "sort",
          "frontier": FRONTIER, "checkpoint-every": CKPT}
    sp.update(over)
    return sp


def _wait_ops_fed(w, n, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while w.ops_fed < n and time.monotonic() < deadline:
        time.sleep(0.02)
    assert w.ops_fed == n


class _Raw:
    """A bare line-JSON protocol client (no ServiceClient smarts)."""

    def __init__(self, addr):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(addr)
        self.rf = self.sock.makefile("r", encoding="utf-8")
        self._rid = 0

    def send(self, msg):
        self.sock.sendall((json.dumps(msg) + "\n").encode())

    def request(self, msg):
        self._rid += 1
        msg = dict(msg, id=self._rid)
        self.send(msg)
        while True:
            line = self.rf.readline()
            assert line, "connection closed awaiting reply"
            r = json.loads(line)
            if r.get("id") == self._rid:
                return r

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def served(tmp_path):
    svc = service.VerificationService()
    addr = svc.serve(str(tmp_path / "svc.sock"))
    yield svc, addr
    svc.stop()


# -- sequence dedup (exactly-once application) ------------------------------

def test_seq_dedup_pin(served):
    """The pin: 9 op sends carrying 6 distinct seqs → exactly 6 ops
    applied, 3 counted as replays, and the ack high-water mark tracks
    the applied prefix."""
    svc, addr = served
    ops, _ = _hist(61)
    c = _Raw(addr)
    r = c.request({"type": "attach", "stream": "s1",
                   "targets": {"linear": _wgl_spec()},
                   "session": "tok-a"})
    assert r["ok"] and r["stream"] == "s1"
    for seq in (1, 2, 3, 2, 3, 4, 5, 4, 6):   # 3 replayed duplicates
        r = c.request({"type": "op", "op": ops[seq - 1], "seq": seq})
        assert r["ok"]
    assert r["acked"] == 6
    w = svc._worker("s1")
    _wait_ops_fed(w, 6)
    st = svc.status()
    assert st["sessions"]["count"] == 1
    assert st["sessions"]["replays"] == 3
    # a garbage seq is dropped, not applied and not an error
    r = c.request({"type": "op", "op": ops[0], "seq": "bogus"})
    assert r["ok"] and r["acked"] == 6
    time.sleep(0.2)
    assert w.ops_fed == 6
    c.close()


def test_ack_flag_without_id(served):
    """ack:true requests an acked reply without allocating a reply id
    — the client's bounded-replay-buffer heartbeat."""
    _svc, addr = served
    ops, _ = _hist(61)
    c = _Raw(addr)
    c.request({"type": "attach", "stream": "s2",
               "targets": {"linear": _wgl_spec()},
               "session": "tok-b"})
    c.send({"type": "op", "op": ops[0], "seq": 1})   # no reply
    c.send({"type": "op", "op": ops[1], "seq": 2, "ack": True})
    r = json.loads(c.rf.readline())
    assert r == {"ok": True, "acked": 2}
    c.close()


def test_session_token_mismatch_refused(served):
    """A live stream must not be hijackable by name: re-attach with a
    different token is refused (the worker keeps running)."""
    svc, addr = served
    c1 = _Raw(addr)
    c1.request({"type": "attach", "stream": "s3",
                "targets": {"linear": _wgl_spec()},
                "session": "tok-owner"})
    c2 = _Raw(addr)
    r = c2.request({"type": "attach", "stream": "s3",
                    "session": "tok-thief", "resume": True})
    assert r["ok"] is False
    assert "token mismatch" in r["error"]
    assert svc._worker("s3") is not None
    c1.close()
    c2.close()


def test_resume_attach_unknown_stream_deferred(served):
    """resume:true for a stream with no worker must refuse (deferred)
    rather than silently re-admit fresh: the dead worker may have
    acked ops this client already forgot."""
    _svc, addr = served
    c = _Raw(addr)
    r = c.request({"type": "attach", "stream": "ghost",
                   "session": "tok-g", "resume": True})
    assert r["ok"] is False and r["deferred"] is True
    assert "not recovered" in r["error"]
    c.close()


def test_legacy_ops_without_seq_still_apply(served):
    """Pre-session clients send ops with no seq: always applied."""
    svc, addr = served
    ops, _ = _hist(61)
    c = _Raw(addr)
    c.request({"type": "attach", "stream": "s4",
               "targets": {"linear": _wgl_spec()},
               "session": "tok-l"})
    for op in ops[:5]:
        c.send({"type": "op", "op": op})
    r = c.request({"type": "poll"})
    assert r["ok"]
    w = svc._worker("s4")
    _wait_ops_fed(w, 5)
    c.close()


# -- client survives injected socket drops ----------------------------------

class _Proxy:
    """A TCP proxy in front of the service socket whose connections
    the test can cut at will — the socket-drop injector."""

    def __init__(self, upstream_addr):
        self.upstream_addr = upstream_addr
        self.ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.ls.bind(("127.0.0.1", 0))
        self.ls.listen(16)
        self.addr = "127.0.0.1:%d" % self.ls.getsockname()[1]
        self._lock = threading.Lock()
        self._conns = []        # guarded-by: _lock
        self.accepted = 0
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                down, _ = self.ls.accept()
            except OSError:
                return
            self.accepted += 1
            up = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                up.connect(self.upstream_addr)
            except OSError:
                down.close()
                continue
            with self._lock:
                self._conns.append((down, up))
            for a, b in ((down, up), (up, down)):
                threading.Thread(target=self._pump, args=(a, b),
                                 daemon=True).start()

    @staticmethod
    def _pump(src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def drop_all(self):
        """Cut every live proxied connection (both directions)."""
        with self._lock:
            conns, self._conns = self._conns, []
        for down, up in conns:
            for s in (down, up):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    def close(self):
        self.drop_all()
        try:
            self.ls.close()
        except OSError:
            pass


def test_client_survives_three_socket_drops(served, tmp_path):
    """The acceptance pin: ServiceClient rides out ≥3 injected drops
    mid-stream with zero duplicated or lost ops — the verdict is
    byte-identical to a solo run and the worker fed exactly len(ops)
    ops (sequence dedup swallowed every replayed duplicate)."""
    svc, addr = served
    ops, solo = _hist(62)
    proxy = _Proxy(addr)
    try:
        c = service.ServiceClient(
            proxy.addr, {"name": "drop", "start-time": "7",
                         "store-dir": str(tmp_path / "cs")},
            spec={"linear": _wgl_spec()})
        quarters = len(ops) // 4
        for i, op in enumerate(ops):
            c.offer(op)
            if i in (quarters, 2 * quarters, 3 * quarters):
                proxy.drop_all()
        res = c.finalize()
        assert c.reconnects >= 3
        assert _strip(res["linear"]) == _strip(solo)
        w = svc._worker("drop/7")
        assert w.ops_fed == len(ops)
        assert svc.status()["sessions"]["replays"] >= 0
        c.close()
    finally:
        proxy.close()


# -- acceptor hardening + protocol fuzz (satellites) ------------------------

def test_oversized_line_answers_and_connection_survives(served):
    """A frame past MAX_LINE_BYTES gets one error reply and the same
    connection keeps working."""
    _svc, addr = served
    c = _Raw(addr)
    c.sock.sendall(b'{"pad": "' + b"x" * (service.MAX_LINE_BYTES + 64)
                   + b'"}\n')
    r = json.loads(c.rf.readline())
    assert r["ok"] is False and "too long" in r["error"]
    r = c.request({"type": "status"})
    assert r["ok"] and r["status"]["state"] == "serving"
    c.close()


def test_malformed_frames_answer_errors(served):
    """Bad json / non-object json / unknown verbs each answer an
    error on a live connection instead of dropping it."""
    _svc, addr = served
    c = _Raw(addr)
    c.sock.sendall(b"{not json at all\n")
    assert json.loads(c.rf.readline())["error"] == "bad json"
    c.sock.sendall(b'[1, 2, 3]\n')
    assert json.loads(c.rf.readline())["error"] == "not an object"
    r = c.request({"type": "warp"})
    assert r["ok"] is False and "unknown type" in r["error"]
    # a verb that explodes server-side is contained too: finish with
    # no attach answers, doesn't kill the thread
    r = c.request({"type": "finish"})
    assert r["ok"] is False and r["error"] == "not attached"
    r = c.request({"type": "poll"})
    assert r["ok"]
    c.close()


def test_protocol_fuzz_daemon_stays_healthy(served):
    """Random bytes, split frames, interleaved verbs, oversized
    lines, and mid-frame disconnects against a live serve() socket:
    the daemon stays healthy throughout and an honest sibling stream
    on the same daemon is unaffected."""
    svc, addr = served
    ops, solo = _hist(61)
    rng = random.Random(1234)

    # the honest sibling, running concurrently with the fuzzer
    sib = _Raw(addr)
    sib.request({"type": "attach", "stream": "honest",
                 "targets": {"linear": _wgl_spec()},
                 "session": "tok-h"})

    verbs = [{"type": "poll"}, {"type": "status"},
             {"type": "attach", "stream": "f", "targets": {}},
             {"type": "op", "op": {"w": 1}, "seq": "NaN"},
             {"type": "finish", "timeout-s": 0.01},
             {"type": "metrics", "compact": True},
             {"type": None}, {"no-type": 1}]
    for trial in range(30):
        f = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        f.connect(addr)
        try:
            kind = trial % 5
            if kind == 0:       # pure garbage bytes
                f.sendall(bytes(rng.randrange(256)
                                for _ in range(rng.randrange(1, 2048))))
            elif kind == 1:     # a frame split across many sends
                data = (json.dumps(verbs[rng.randrange(len(verbs))])
                        + "\n").encode()
                for i in range(0, len(data), 3):
                    f.sendall(data[i:i + 3])
                    time.sleep(0.001)
            elif kind == 2:     # interleaved valid verbs
                for _ in range(rng.randrange(1, 6)):
                    f.sendall((json.dumps(
                        verbs[rng.randrange(len(verbs))])
                        + "\n").encode())
            elif kind == 3:     # mid-frame disconnect
                f.sendall(b'{"type": "attach", "stream": "tru')
            else:               # oversized frame then a valid verb
                f.sendall(b'"' + b"A" * (service.MAX_LINE_BYTES + 1)
                          + b'"\n{"type": "poll"}\n')
        except OSError:
            pass                # the daemon may hang up; that's fine
        finally:
            f.close()
        if trial % 10 == 0:     # the sibling makes live progress
            for op in ops[trial:trial + 10]:
                sib.send({"type": "op", "op": op})

    # daemon healthy after the storm
    st = svc.status()
    assert st["state"] == "serving"
    # the storm interleaved ops[0:30] (10 per tenth trial, in order);
    # feed the rest and the sibling's verdict matches solo exactly
    for op in ops[30:]:
        sib.send({"type": "op", "op": op})
    r = sib.request({"type": "finish", "timeout-s": 300})
    assert r["ok"], r
    assert _strip(r["results"]["linear"]) == _strip(solo)
    sib.close()


# -- bounded session table (ISSUE 20 satellite) -----------------------------

def test_terminal_stream_evicts_session(served):
    """A stream reaching its verdict frees its session entry
    immediately — no client can resume a finished stream onto a live
    worker, so keeping the token + high-water mark is pure growth."""
    svc, addr = served
    ops, solo = _hist(61)
    c = _Raw(addr)
    r = c.request({"type": "attach", "stream": "evict/1",
                   "targets": {"linear": _wgl_spec()},
                   "session": "tok-e"})
    assert r["ok"]
    for seq, op in enumerate(ops, 1):
        c.send({"type": "op", "op": op, "seq": seq})
    with svc._session_lock:
        assert "evict/1" in svc._sessions
    r = c.request({"type": "finish", "timeout-s": 300})
    assert r["ok"]
    assert _strip(r["results"]["linear"]) == _strip(solo)
    with svc._session_lock:
        assert "evict/1" not in svc._sessions
    c.close()


def test_session_ttl_sweep():
    """Sessions idle past the TTL with no live worker are swept;
    a session whose stream is still streaming survives any idle."""
    svc = service.VerificationService(adaptive=False,
                                      session_ttl_s=0.05)
    try:
        ops, _ = _hist(61)
        # a ghost session: its stream never had a worker (the client
        # died between attach and first op)
        assert svc._session_attach("ghost/1", "tok-g", False)
        # a live one: worker admitted and not done
        svc.admit("live/1", {"linear": _wgl_spec()})
        assert svc._session_attach("live/1", "tok-l", False)
        svc.offer("live/1", ops[0])
        time.sleep(0.1)
        svc._prune_sessions()
        with svc._session_lock:
            assert "ghost/1" not in svc._sessions
            assert "live/1" in svc._sessions
        svc.seal("live/1")
        assert svc._worker("live/1").done.wait(60.0)
    finally:
        svc.stop()


def test_session_table_size_backstop():
    """Even inside the TTL, the table cannot grow unboundedly: past
    the size gate, entries with no known worker are dropped."""
    svc = service.VerificationService(adaptive=False)
    try:
        n = max(256, 4 * svc.keep_done) + 10
        for i in range(n):
            svc._session_attach(f"g/{i}", f"tok-{i}", False)
        svc._prune_sessions()
        with svc._session_lock:
            assert len(svc._sessions) <= max(256, 4 * svc.keep_done)
    finally:
        svc.stop()
