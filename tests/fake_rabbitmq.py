"""An in-process fake of RabbitMQ's management HTTP API (the slice the
rabbitmq suite's client uses: queue declare, publish, get with
ack_requeue_false), backed by in-memory queues."""

from __future__ import annotations

import collections
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _json(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(n)) if n else {}

    def do_PUT(self):
        srv: "FakeRabbitMQ" = self.server  # type: ignore[assignment]
        parts = self.path.strip("/").split("/")
        if parts[:2] == ["api", "queues"] and len(parts) == 4:
            with srv.lock:
                srv.queues.setdefault(parts[3], collections.deque())
            return self._json(201, {})
        self._json(404, {"error": "not found"})

    def do_POST(self):
        srv: "FakeRabbitMQ" = self.server  # type: ignore[assignment]
        body = self._body()
        if srv.fail_hook:
            err = srv.fail_hook(self.path, body)
            if err:
                return self._json(500, {"error": err})
        parts = self.path.strip("/").split("/")
        if "publish" in parts:
            q = body["routing_key"]
            with srv.lock:
                srv.queues.setdefault(
                    q, collections.deque()).append(body["payload"])
            return self._json(200, {"routed": True})
        if parts[-1] == "get":
            q = parts[3]
            out = []
            with srv.lock:
                dq = srv.queues.setdefault(q, collections.deque())
                for _ in range(body.get("count", 1)):
                    if not dq:
                        break
                    out.append({"payload": dq.popleft(),
                                "payload_encoding": "string"})
            return self._json(200, out)
        self._json(404, {"error": "not found"})


class FakeRabbitMQ(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _Handler)
        self.queues: dict = {}
        self.lock = threading.Lock()
        self.fail_hook = None  # fail_hook(path, body) -> err str | None
        self.port = self.server_address[1]
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()
