"""Shared socket plumbing for the in-process protocol fakes."""

from __future__ import annotations

import socket
import socketserver


class NodelayHandler(socketserver.BaseRequestHandler):
    """Base handler disabling Nagle on the accepted socket: the fakes
    speak strict request/response protocols, where Nagle + delayed ACK
    otherwise cost ~40ms per round trip."""

    def setup(self):
        self.request.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
