"""ABFT attestation: silent-corruption detection + recovery.

The contract under test (checker/abft.py, checker/wgl.py,
checker/streaming.py, _platform.py): with
``JEPSEN_TPU_FAULT_INJECT=bitflip@site:n`` armed, every attested
device entry — offline, batch, sharded, stream-chunk, elle — detects
the corrupted staged buffer via digest mismatch, classifies it as the
``corrupt`` fault kind, and the recovery ladder re-stages/replays so
the verdict is identical to an uninjected run's. Shapes are shared
with tests/test_recovery.py (chunk 128, 8 slots, seed-13 histories)
so tier-0/tier-1 pay each kernel compile once.
"""

from __future__ import annotations

import numpy as np
import pytest

import jepsen_tpu._platform as plat
import jepsen_tpu.control.retry as retry
from jepsen_tpu import models
from jepsen_tpu.checker import abft, streaming, synth, wgl

MODEL = models.cas_register()
CHUNK = 128
SLOTS = 8


@pytest.fixture(autouse=True)
def _fast_deterministic_faults(monkeypatch):
    monkeypatch.setattr(retry, "backoff",
                        lambda *a, **k: iter([0.0] * 1000))
    monkeypatch.delenv(plat.FAULT_INJECT_ENV, raising=False)
    monkeypatch.delenv(plat.ATTEST_ENV, raising=False)
    plat.reset_fault_injection()
    yield
    plat.fault_hook = None
    plat.corrupt_hook = None
    plat.reset_fault_injection()


def _hist(seed=13, n=400, conc=4):
    return synth.register_history(n, concurrency=conc, values=5,
                                  seed=seed)


# -- the injection shim -----------------------------------------------------

def test_bitflip_clause_corrupts_nth_staging_once(monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@s:2")
    a = np.arange(16, dtype=np.int32)
    assert plat.maybe_corrupt("s", a) is a          # staging 1: clean
    b = plat.maybe_corrupt("s", a)                  # staging 2: flipped
    assert b is not a and (b != a).sum() == 1
    assert plat.maybe_corrupt("s", a) is a          # spent
    assert plat.maybe_corrupt("other", a) is a      # other site: never


def test_bitflip_clause_never_raises_in_inject_fault(monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@s:1")
    plat.maybe_inject_fault("s")    # must not raise


def test_corrupt_hook_beats_env(monkeypatch):
    calls = []

    def hook(site, arr):
        calls.append(site)
        return plat.flip_bit(arr)

    monkeypatch.setattr(plat, "corrupt_hook", hook)
    a = np.zeros(8, np.int32)
    b = plat.maybe_corrupt("x", a)
    assert calls == ["x"] and (b != a).any()


def test_flip_bit_changes_exactly_one_bit():
    a = np.arange(32, dtype=np.int32)
    b = plat.flip_bit(a)
    diff = np.bitwise_xor(a.view(np.uint32), b.view(np.uint32))
    assert (diff != 0).sum() == 1
    assert bin(int(diff[diff != 0][0])).count("1") == 1


def test_classifier_buckets_corrupt():
    e = plat.CorruptDeviceResult("offline", "digest mismatch")
    assert plat.classify_backend_error(e) == plat.FAULT_CORRUPT
    assert plat.FAULT_CORRUPT in plat.FAULT_KINDS


# -- digest parity (no false positives) -------------------------------------

@pytest.mark.parametrize("dtype", [np.int32, np.uint32, np.float32])
def test_host_device_digest_parity(dtype):
    rng = np.random.default_rng(7)
    a = rng.integers(-2 ** 31, 2 ** 31 - 1, (37, 11),
                     dtype=np.int64).astype(np.int32).view(dtype)
    import jax.numpy as jnp
    dev = int(np.asarray(abft.digest_device(jnp.asarray(a))))
    assert dev == abft.digest_host(a)


def test_digest_detects_any_single_bitflip():
    a = np.arange(64, dtype=np.int32)
    d0 = abft.digest_host(a)
    for bit in (0, 12, 31):
        assert abft.digest_host(plat.flip_bit(a, bit)) != d0


def test_attest_enabled_gate(monkeypatch):
    assert plat.attest_enabled() is True            # default on
    monkeypatch.setenv(plat.ATTEST_ENV, "0")
    assert plat.attest_enabled() is False
    assert plat.attest_enabled(True) is True        # override beats env


# -- offline / batch / sharded: detection + identical verdicts --------------

@pytest.fixture(scope="module")
def offline_baseline():
    return wgl.analysis_tpu(MODEL, _hist())


@pytest.mark.parametrize("engine", ["dense", "sort"])
def test_offline_bitflip_recovers_identically(engine, offline_baseline,
                                              monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@offline:1")
    a = wgl.analysis_tpu(MODEL, _hist(), engine=engine)
    assert a["valid?"] == offline_baseline["valid?"] is True
    assert a["recovered"]["faults"] == ["corrupt"]
    assert a["attested"]["steps"] == 1


def test_offline_chunked_verifies_carry_digest():
    a = wgl.analysis_tpu(MODEL, _hist(), chunk_entries=256)
    assert a["valid?"] is True
    assert a["attested"]["carry"] >= 1


def test_offline_attest_off_documents_the_knob(monkeypatch):
    # with attestation disabled the bitflip ships undetected: no
    # 'corrupt' fault, no 'attested' stamp — the knob exists to
    # measure the unguarded baseline, and this is its cost
    monkeypatch.setenv(plat.ATTEST_ENV, "0")
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@offline:1")
    a = wgl.analysis_tpu(MODEL, _hist())
    assert "recovered" not in a
    assert "attested" not in a


BATCH_SEEDS = (10, 11, 12, 13)


def _batch_hists():
    return [_hist(seed=s, n=120, conc=3) for s in BATCH_SEEDS]


@pytest.fixture(scope="module")
def batch_baseline():
    return [r["valid?"] for r in
            wgl.analysis_tpu_batch(MODEL, _batch_hists())]


def test_batch_bitflip_recovers_identically(batch_baseline,
                                            monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@batch:1")
    rs = wgl.analysis_tpu_batch(MODEL, _batch_hists())
    assert [r["valid?"] for r in rs] == batch_baseline
    assert any(r.get("recovered", {}).get("faults") == ["corrupt"]
               for r in rs)
    assert all(r.get("attested") for r in rs)


def test_sharded_bitflip_recovers_identically(monkeypatch):
    ok0, pk0 = wgl.check_batch_sharded(MODEL, _batch_hists())
    plat.reset_fault_injection()
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@sharded:1")
    ok, pk, info = wgl.check_batch_sharded(MODEL, _batch_hists(),
                                           return_info=True)
    assert ok == ok0 and (pk == pk0).all()
    assert info["recovered"]["faults"][0] == "corrupt"
    assert info["attested"]["steps"] >= 1


# -- stream-chunk: checkpointed resume with byte-identical stream -----------

def _stream(hist, family, env=None, monkeypatch=None, **kw):
    if env and monkeypatch is not None:
        monkeypatch.setenv(plat.FAULT_INJECT_ENV, env)
    plat.reset_fault_injection()
    s = streaming.WglStream(
        MODEL, chunk_entries=CHUNK, slots=SLOTS, checkpoint_every=2,
        engine=family,
        state_range=(-1, 4) if family == "dense" else None, **kw)
    for op in hist.ops:
        s.feed(op)
    return s, s.finish()


def _stream_bytes(s):
    return (np.concatenate(s._steps_log) if s._steps_log
            else np.zeros((0, 1), np.int32))


@pytest.mark.parametrize("family", ["sort", "dense"])
def test_stream_bitflip_resumes_identically(family, monkeypatch):
    s0, r0 = _stream(_hist(), family)
    assert r0["valid?"] is True and r0["attested"]["steps"] >= 1
    s1, r1 = _stream(_hist(), family, env="bitflip@stream-chunk:3",
                     monkeypatch=monkeypatch)
    assert r1["valid?"] is True
    rec = r1["recovered"]
    assert rec["faults"] == ["corrupt"] and rec["retries"] == 1
    assert rec["resumed-from-chunk"] == 2
    b0, b1 = _stream_bytes(s0), _stream_bytes(s1)
    assert b0.shape == b1.shape and (b0 == b1).all()


def test_stream_bitflip_preserves_blame(monkeypatch):
    bad = synth.corrupt(_hist(), seed=3)
    s0, r0 = _stream(bad, "sort")
    s1, r1 = _stream(bad, "sort", env="bitflip@stream-chunk:2",
                     monkeypatch=monkeypatch)
    assert r0["valid?"] is False and r1["valid?"] is False
    assert r1["op-index"] == r0["op-index"]


def test_stream_checkpoint_is_never_corrupt(monkeypatch):
    # a flip in the chunk FEEDING a checkpoint must be detected at (or
    # before) the checkpoint fetch, so the stored checkpoint is clean
    # and recovery resumes from good state — checked implicitly by the
    # identical-verdict assertions; here we pin that a corrupt fault
    # detected at checkpoint time falls back to the previous one
    s, r = _stream(_hist(n=1000), "sort", env="bitflip@stream-chunk:4",
                   monkeypatch=monkeypatch)
    assert r["valid?"] is True
    assert r["recovered"]["faults"] == ["corrupt"]


# -- elle: adjacency-stack digests + host-mirror final rung -----------------

_CYCLE = {(0, 1): frozenset({"ww"}), (1, 2): frozenset({"wr"}),
          (2, 0): frozenset({"rw"})}
_FLAG_KEYS = ("G0", "G1c", "G-single", "G2-item")


def test_elle_bitflip_detected_and_flags_identical(monkeypatch):
    from jepsen_tpu.checker.elle import kernels
    base = kernels.analyze_edges(3, dict(_CYCLE))
    plat.reset_fault_injection()
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "bitflip@elle:1")
    hits = []
    monkeypatch.setattr(plat, "corrupt_hook",
                        lambda site, arr: hits.append(site) or None)
    got = kernels.analyze_edges(3, dict(_CYCLE))
    assert {k: got[k] for k in _FLAG_KEYS} \
        == {k: base[k] for k in _FLAG_KEYS}
    assert "elle" in hits                   # staging really happened


def test_elle_persistent_corruption_takes_host_mirror(monkeypatch):
    from jepsen_tpu.checker.elle import kernels
    base = kernels.analyze_edges(3, dict(_CYCLE))
    monkeypatch.setattr(
        plat, "corrupt_hook",
        lambda site, arr: plat.flip_bit(arr) if site == "elle"
        else None)
    got = kernels.analyze_edges(3, dict(_CYCLE))
    assert {k: got[k] for k in _FLAG_KEYS} \
        == {k: base[k] for k in _FLAG_KEYS}


# -- carry digest host mirror ----------------------------------------------

def test_verify_carry_catches_att_and_count(monkeypatch):
    import jax.numpy as jnp
    k = wgl._kernel("cas-register", 16, 8, 64, None)
    carry = k.init_carry(jnp.int32(-1))
    import jax
    host = jax.device_get(carry)
    dig = int(jax.device_get(k.digest(carry)))
    abft.verify_carry("t", dig, host)       # clean carry passes
    # corrupt att
    bad = list(host)
    bad[-3] = np.int32(1)
    with pytest.raises(plat.CorruptDeviceResult):
        abft.verify_carry("t", abft.carry_digest_host(tuple(bad)),
                          tuple(bad))
    # digest mismatch
    with pytest.raises(plat.CorruptDeviceResult):
        abft.verify_carry("t", dig ^ 1, host)
