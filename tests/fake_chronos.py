"""In-process Chronos fake: an HTTP scheduler endpoint
(POST /scheduler/iso8601) plus a run-log simulator that answers the
dummy remote's `ls`/`cat` commands with the tempfile logs a correctly
behaving scheduler would have produced — every scheduled run that is
due by "now" has a log with name/start/end lines (end omitted while a
run is still in flight). Set ``drop`` to make the scheduler silently
skip that many due runs (the failure the job-run checker exists to
catch)."""

from __future__ import annotations

import datetime
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _parse_iso(s: str) -> float:
    return datetime.datetime.strptime(
        s, "%Y-%m-%dT%H:%M:%SZ").replace(
        tzinfo=datetime.timezone.utc).timestamp()


class FakeChronos:
    def __init__(self, drop: int = 0):
        self.jobs: list[dict] = []
        self.drop = drop
        self.lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n))
                m = re.match(r"R(\d+)/(.+)/PT(\d+)S", body["schedule"])
                sleep = re.search(r"sleep (\d+)", body["command"])
                with fake.lock:
                    fake.jobs.append({
                        "name": int(body["name"]),
                        "count": int(m.group(1)),
                        "start": _parse_iso(m.group(2)),
                        "interval": int(m.group(3)),
                        "duration": int(sleep.group(1)) if sleep else 0,
                    })
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # -- run-log simulation (the dummy remote's ls/cat target) ---------------

    def _due_runs(self) -> list[dict]:
        now = time.time()
        runs = []
        with self.lock:
            jobs = list(self.jobs)
            drop = self.drop
        for j in jobs:
            for k in range(j["count"]):
                t0 = j["start"] + k * j["interval"]
                if t0 > now:
                    break
                run = {"file": f"run-{j['name']}-{k}",
                       "name": j["name"], "start": t0 + 0.01}
                if t0 + 0.01 + j["duration"] <= now:
                    run["end"] = t0 + 0.01 + j["duration"]
                runs.append(run)
        if drop:
            runs = runs[drop:]
        return runs

    def remote_responder(self, context: dict, action: dict) -> dict:
        cmd = action.get("cmd", "")
        if re.search(r"\bls\b", cmd):
            return {"exit": 0, "out": "\n".join(
                r["file"] for r in self._due_runs())}
        m = re.search(r"\bcat\b.*?(run-\d+-\d+)", cmd)
        if m:
            for r in self._due_runs():
                if r["file"] == m.group(1):
                    lines = [str(r["name"]), f"{r['start']:.3f}"]
                    if "end" in r:
                        lines.append(f"{r['end']:.3f}")
                    return {"exit": 0, "out": "\n".join(lines) + "\n"}
            return {"exit": 1, "err": "No such file"}
        return {"exit": 0, "out": ""}
