"""Tests for the rendering layer: perf graphs, HTML timeline, clock
plots, and the SVG plot library — mirroring the reference's
perf_test.clj approach of rendering from synthetic histories, plus
structural assertions on the emitted artifacts."""

import math
import os

import pytest

import importlib

import jepsen_tpu.checker.clock as cclock
import jepsen_tpu.checker.timeline as timeline
from jepsen_tpu import plot as gp

# `checker.perf` the attribute is the composed-checker factory
# (reference-parity API); the module itself lives in sys.modules.
perf = importlib.import_module("jepsen_tpu.checker.perf")
from jepsen_tpu import store, util
from jepsen_tpu.checker import clock_plot, latency_graph, rate_graph
from jepsen_tpu.history import history


def synth_history(n=200, procs=4, dt_ns=25_000_000):
    """A deterministic invoke/complete history with some fails/infos and
    a nemesis start/stop window."""
    ops, t = [], 0
    for i in range(n):
        p = i % procs
        f = ("read", "write", "cas")[i % 3]
        t += dt_ns
        ops.append({"type": "invoke", "f": f, "process": p, "time": t,
                    "value": None})
        typ = ("ok", "ok", "ok", "fail", "info")[i % 5]
        ops.append({"type": typ, "f": f, "process": p,
                    "time": t + dt_ns // 2, "value": i % 5})
    mid = ops[len(ops) // 2]["time"]
    ops += [
        {"type": "invoke", "f": "start", "process": "nemesis",
         "time": mid, "value": None},
        {"type": "info", "f": "start", "process": "nemesis",
         "time": mid + dt_ns, "value": "partitioned"},
        {"type": "invoke", "f": "stop", "process": "nemesis",
         "time": mid + 20 * dt_ns, "value": None},
        {"type": "info", "f": "stop", "process": "nemesis",
         "time": mid + 21 * dt_ns, "value": "healed"},
    ]
    ops.sort(key=lambda o: o["time"])
    return history(ops).index()


@pytest.fixture
def test_map(tmp_path):
    return {"name": "perf-test", "start-time": "t0",
            "store-dir": str(tmp_path / "store")}


# -- bucketing / quantiles (perf.clj:21-86 semantics) -----------------------

def test_bucket_scale_midpoints():
    assert perf.bucket_scale(10, 0) == 5
    assert perf.bucket_scale(10, 1) == 15
    assert perf.bucket_time(10, 7) == 5
    assert perf.bucket_time(10, 13) == 15
    assert perf.buckets(10, 30) == [5, 15, 25]


def test_quantiles():
    qs = perf.quantiles([0, 0.5, 1], [3, 1, 2, 4, 5])
    assert qs == {0: 1, 0.5: 3, 1: 5}
    assert perf.quantiles([0.5], []) == {}


def test_latencies_to_quantiles():
    pts = [(1, 10), (2, 20), (11, 100), (12, 300)]
    out = perf.latencies_to_quantiles(10, [1.0], pts)
    assert out == {1.0: [[5.0, 20], [15.0, 300]]}


def test_invokes_by_f_type():
    h = util.history_latencies(synth_history(20))
    by = perf.invokes_by_f_type(h)
    assert {"read", "write", "cas"} <= set(by)
    for f in by:
        for t in ("ok", "fail", "info"):
            for o in by[f][t]:
                assert o["completion"]["type"] == t


def test_rate_totals():
    h = synth_history(30)
    r = perf.rate(h)
    total = r["all"]["all"]
    assert total == sum(v for f, m in r.items() if f != "all"
                       for t, v in m.items() if t != "all")


# -- nemesis activity -------------------------------------------------------

def test_nemesis_activity_intervals():
    h = synth_history(100)
    acts = perf.nemesis_activity(None, h)
    assert len(acts) == 1
    n = acts[0]
    assert n["name"] == "nemesis"
    assert len(n["ops"]) == 4
    assert len(n["intervals"]) == 2  # invoke-pair + completion-pair
    for a, b in n["intervals"]:
        assert a["f"] == "start" and b["f"] == "stop"


def test_named_nemesis_spec():
    h = synth_history(100)
    acts = perf.nemesis_activity(
        [{"name": "partitions", "start": ["start"], "stop": ["stop"],
          "color": "#ff0000"}], h)
    assert [a["name"] for a in acts] == ["partitions"]


# -- SVG plot library -------------------------------------------------------

def test_broaden_range():
    lo, hi = gp.broaden_range((0.3, 9.7))
    assert lo <= 0.3 and hi >= 9.7
    assert gp.broaden_range((5, 5)) == (4, 6)


def test_render_basic_svg():
    p = gp.Plot(title="t", ylabel="y")
    p.series.append(gp.Series(title="s1", data=[(0, 1), (1, 2), (2, 4)],
                              mode="linespoints"))
    svg = gp.render(p)
    assert svg.startswith("<svg")
    assert "s1" in svg and "</svg>" in svg


def test_render_log_scale():
    p = gp.Plot(logscale_y=True)
    p.series.append(gp.Series(title=None,
                              data=[(0, 0.1), (1, 10), (2, 1000)]))
    svg = gp.render(p)
    assert "<svg" in svg


def test_no_points():
    p = gp.Plot()
    p.series.append(gp.Series(title="empty", data=[]))
    with pytest.raises(gp.NoPoints):
        gp.render(p)
    assert gp.write(p, "/nonexistent/should-not-write.svg") is None


# -- graph checkers end to end ----------------------------------------------

def test_point_and_quantile_graphs(test_map):
    h = synth_history(300)
    res = latency_graph().check(test_map, h, {})
    assert res["valid?"] is True
    raw = store.path(test_map, "latency-raw.svg")
    q = store.path(test_map, "latency-quantiles.svg")
    assert os.path.exists(raw) and os.path.exists(q)
    svg = open(raw).read()
    # nemesis shading + all three completion types present
    assert "opacity" in svg
    assert "read ok" in svg and "cas fail" in svg and "write info" in svg


def test_rate_graph(test_map):
    h = synth_history(300)
    res = rate_graph().check(test_map, h, {})
    assert res["valid?"] is True
    svg = open(store.path(test_map, "rate.svg")).read()
    assert "Throughput" in svg


def test_perf_compose(test_map):
    res = perf.perf_checker().check(test_map, synth_history(300), {})
    assert res["valid?"] is True
    for f in ("latency-raw.svg", "latency-quantiles.svg", "rate.svg"):
        assert os.path.exists(store.path(test_map, f))


def test_graphs_subdirectory(test_map):
    latency_graph().check(test_map, synth_history(60),
                          {"subdirectory": "k1"})
    assert os.path.exists(store.path(test_map, "k1", "latency-raw.svg"))


def test_empty_history_graphs(test_map):
    assert latency_graph().check(test_map, history([]), {})["valid?"] \
        is True
    assert rate_graph().check(test_map, history([]), {})["valid?"] is True


# -- timeline ---------------------------------------------------------------

def test_timeline_pairs():
    h = [{"type": "invoke", "f": "r", "process": 0, "time": 1},
         {"type": "ok", "f": "r", "process": 0, "time": 2},
         {"type": "invoke", "f": "w", "process": 1, "time": 3},
         {"type": "info", "f": "w", "process": 1, "time": 4},
         {"type": "info", "f": "kill", "process": "nemesis", "time": 5}]
    ps = timeline.pairs(h)
    assert [len(p) for p in ps] == [2, 2, 1]
    assert ps[2][0]["f"] == "kill"


def test_timeline_html(test_map):
    h = synth_history(100)
    res = timeline.html().check(test_map, h, {})
    assert res["valid?"] is True
    doc = open(store.path(test_map, "timeline.html")).read()
    assert "<html>" in doc
    assert 'class="op ok"' in doc and 'class="op fail"' in doc
    assert "Showing only" not in doc  # under the cap


def test_timeline_truncation(test_map, monkeypatch):
    monkeypatch.setattr(timeline, "OP_LIMIT", 10)
    h = synth_history(100)
    timeline.html().check(test_map, h, {})
    doc = open(store.path(test_map, "timeline.html")).read()
    assert "Showing only 10" in doc


def test_timeline_process_index():
    h = [{"process": 3}, {"process": "nemesis"}, {"process": 1},
         {"process": 3}]
    idx = timeline.process_index(h)
    assert idx[1] == 0 and idx[3] == 1 and idx["nemesis"] == 2


# -- clock plots ------------------------------------------------------------

def test_clock_datasets():
    h = [{"type": "info", "f": "check-offsets", "process": "nemesis",
          "time": util.secs_to_nanos(1),
          "clock-offsets": {"n1": 0.5, "n2": -0.25}},
         {"type": "info", "f": "check-offsets", "process": "nemesis",
          "time": util.secs_to_nanos(5),
          "clock-offsets": {"n1": 1.5}},
         {"type": "ok", "f": "read", "process": 0,
          "time": util.secs_to_nanos(9)}]
    ds = cclock.history_to_datasets(h)
    assert ds["n1"] == [[1.0, 0.5], [5.0, 1.5], [9.0, 1.5]]
    assert ds["n2"] == [[1.0, -0.25], [9.0, -0.25]]


def test_short_node_names():
    assert cclock.short_node_names(
        ["n1.foo.com", "n2.foo.com"]) == ["n1", "n2"]
    assert cclock.short_node_names(["a", "b"]) == ["a", "b"]


def test_clock_plot_checker(test_map):
    h = history([
        {"type": "info", "f": "check-offsets", "process": "nemesis",
         "time": util.secs_to_nanos(i),
         "clock-offsets": {"n1": math.sin(i), "n2": 0.1 * i}}
        for i in range(1, 20)])
    res = clock_plot().check(test_map, h, {})
    assert res["valid?"] is True
    svg = open(store.path(test_map, "clock-skew.svg")).read()
    assert "clock skew" in svg and "n1" in svg


def test_clock_plot_empty(test_map):
    assert clock_plot().check(test_map, history([]), {})["valid?"] is True


def test_adaptive_dt_scales_with_duration():
    from jepsen_tpu.checker.perf import adaptive_dt

    def hist_of(seconds):
        return [{"time": int(seconds * 1e9), "type": "ok", "f": "r",
                 "process": 0, "value": None}]

    assert adaptive_dt(hist_of(60)) == 1       # 1 min test: 1s buckets
    assert adaptive_dt(hist_of(600)) == 10     # 10 min: 10s
    assert adaptive_dt(hist_of(86400)) == 1800  # day-long soak
    assert adaptive_dt([]) == 1


def test_dense_point_series_render_translucent():
    from jepsen_tpu import plot as gp

    dense = gp.Plot(series=[gp.Series(
        title="d", data=[(i, i % 7) for i in range(gp.DENSE_POINTS + 1)],
        mode="points")])
    # fill-opacity (a presentation attribute, applied per marker) —
    # NOT group `opacity`, which would composite the layer as one unit
    # and flatten the overlaps the translucency exists to show
    assert f'fill-opacity="{gp.DENSE_ALPHA}"' in gp.render(dense)
    sparse = gp.Plot(series=[gp.Series(
        title="s", data=[(0, 1), (1, 2)], mode="points")])
    assert f'opacity="{gp.DENSE_ALPHA}"' not in gp.render(sparse)
