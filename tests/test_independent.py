"""Key-sharded (independent) generator + checker tests, mirroring the
reference's `jepsen/test/jepsen/independent_test.clj`."""

import jepsen_tpu.generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import linearizable
from jepsen_tpu.generator.simulate import n_plus_nemesis_context, quick
from jepsen_tpu.history import history
from jepsen_tpu.independent import (
    KV, concurrent_generator, history_keys, ktuple, sequential_generator,
    subhistory, tuple_key, tuple_value,
)
from jepsen_tpu.models import cas_register


def test_tuple():
    t = ktuple("k", 3)
    assert isinstance(t, KV)
    assert t.key == "k" and t.value == 3
    assert t == ("k", 3)  # still a tuple
    op = {"value": t}
    assert tuple_key(op) == "k"
    assert tuple_value(op) == 3
    assert tuple_key({"value": ("k", 3)}) is None  # plain pairs don't count


def test_sequential_generator():
    g = sequential_generator(
        [0, 1], lambda k: gen.limit(2, gen.repeat({"f": "read", "value": None})))
    ops = quick(n_plus_nemesis_context(2), gen.clients(g))
    assert [o["value"] for o in ops] == [
        KV(0, None), KV(0, None), KV(1, None), KV(1, None)]


def test_sequential_generator_exhausts():
    g = sequential_generator([], lambda k: {"f": "read"})
    assert quick(n_plus_nemesis_context(2), gen.clients(g)) == []


def test_concurrent_generator_partitions_threads():
    # 4 client threads, 2 per key: two keys run concurrently.
    g = concurrent_generator(
        2, iter(range(100)), lambda k: gen.limit(3, gen.repeat({"f": "w", "value": k})))
    ops = quick(n_plus_nemesis_context(4),
                gen.clients(gen.limit(12, g)))
    assert len(ops) == 12
    for o in ops:
        v = o["value"]
        assert isinstance(v, KV)
        assert v.value == v.key  # fgen closed over the right key
    # both groups made progress concurrently
    keys_by_group = {}
    for o in ops:
        keys_by_group.setdefault(o["process"] % 4 // 2,
                                 set()).add(o["value"].key)
    assert len(keys_by_group) == 2
    assert not (keys_by_group[0] & keys_by_group[1])


def test_concurrent_generator_rolls_to_next_key():
    # 2 threads, 1 group, keys exhaust one after another
    g = concurrent_generator(
        2, [10, 20], lambda k: gen.limit(2, gen.repeat({"f": "w", "value": k})))
    ops = quick(n_plus_nemesis_context(2), gen.clients(g))
    assert [o["value"] for o in ops] == [
        KV(10, 10), KV(10, 10), KV(20, 20), KV(20, 20)]


def test_concurrent_generator_divisibility():
    g = concurrent_generator(2, [1], lambda k: {"f": "r"})
    try:
        quick(n_plus_nemesis_context(3), gen.clients(g))
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "divisible" in str(e)


def _kv_history():
    """Two keys; key 'a' linearizable, key 'b' not (read sees a value
    never written)."""
    ops = []
    t = [0]

    def add(process, typ, f, k, v):
        t[0] += 1
        ops.append({"type": typ, "f": f, "value": KV(k, v),
                    "process": process, "time": t[0]})

    add(0, "invoke", "write", "a", 1)
    add(0, "ok", "write", "a", 1)
    add(0, "invoke", "read", "a", None)
    add(0, "ok", "read", "a", 1)
    add(1, "invoke", "write", "b", 1)
    add(1, "ok", "write", "b", 1)
    add(1, "invoke", "read", "b", None)
    add(1, "ok", "read", "b", 2)  # never written!
    return history(ops)


def test_history_keys_and_subhistory():
    h = _kv_history()
    assert history_keys(h) == ["a", "b"]
    sub = subhistory("a", h)
    assert len(sub) == 4
    assert all(not isinstance(o["value"], KV) for o in sub)
    assert sub[3]["value"] == 1


def test_subhistory_keeps_nemesis_ops():
    h = history([
        {"type": "invoke", "f": "w", "value": KV("a", 1), "process": 0},
        {"type": "info", "f": "start", "value": None, "process": "nemesis"},
        {"type": "ok", "f": "w", "value": KV("a", 1), "process": 0},
    ])
    sub = subhistory("a", h)
    assert len(sub) == 3
    assert sub[1]["process"] == "nemesis"


def test_independent_checker_host():
    c = independent.checker(linearizable(cas_register(), "host"))
    res = c.check({}, _kv_history(), {})
    assert res["valid?"] is False
    assert res["failures"] == ["b"]
    assert res["results"]["a"]["valid?"] is True
    assert res["results"]["b"]["valid?"] is False


def test_independent_checker_tpu_batched():
    c = independent.checker(linearizable(cas_register(), "auto"))
    res = c.check({}, _kv_history(), {})
    assert res["valid?"] is False
    assert res["failures"] == ["b"]
    assert res["results"]["a"]["valid?"] is True
    assert res["results"]["b"]["valid?"] is False
    # the batched path actually ran on device
    assert "tpu" in res["results"]["a"]["analyzer"]


def test_independent_strict_device_raises_and_default_falls_back(
        caplog, monkeypatch):
    import logging
    import pytest
    from jepsen_tpu.checker import wgl

    def boom(*a, **k):
        raise RuntimeError("simulated kernel breakage")

    monkeypatch.setattr(wgl, "analysis_tpu_batch", boom)
    c = linearizable(cas_register(), "auto")
    with pytest.raises(RuntimeError, match="simulated"):
        independent.checker(c, strict_device=True).check(
            {}, _kv_history(), {})
    # default: loud warning, correct per-key fallback verdict
    with caplog.at_level(logging.WARNING, "jepsen_tpu.independent"):
        res = independent.checker(c).check({}, _kv_history(), {})
    assert res["valid?"] is False and res["failures"] == ["b"]
    assert any("falling back" in r.message for r in caplog.records)


def test_concurrent_generator_skips_empty_key_generators():
    # keys 0-1 yield empty generators; productive keys must still run
    def fgen(k):
        if k < 2:
            return None
        return gen.limit(2, gen.repeat({"f": "w", "value": k}))

    g = concurrent_generator(2, iter(range(4)), fgen)
    ops = quick(n_plus_nemesis_context(2), gen.clients(g))
    assert [o["value"] for o in ops] == [
        KV(2, 2), KV(2, 2), KV(3, 3), KV(3, 3)]
