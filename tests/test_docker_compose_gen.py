"""Parametric docker-compose generation (reference:
`docker/bin/build-docker-compose:1-32` — %%N%% templating over
template fragments so node count is a parameter, not a hardcoded 5)."""

import shutil
import subprocess
from pathlib import Path

import pytest
import yaml

DOCKER_DIR = Path(__file__).resolve().parent.parent / "docker"


def _gen(tmp_path, n):
    work = tmp_path / "docker"
    work.mkdir()
    shutil.copytree(DOCKER_DIR / "template", work / "template")
    shutil.copytree(DOCKER_DIR / "bin", work / "bin")
    res = subprocess.run(
        ["sh", str(work / "bin" / "gen-compose"), str(n)],
        capture_output=True, text=True)
    return res, work / "docker-compose.yml"


@pytest.mark.parametrize("n", [1, 3, 7])
def test_gen_compose_n_nodes(tmp_path, n):
    res, out = _gen(tmp_path, n)
    assert res.returncode == 0, res.stderr
    d = yaml.safe_load(out.read_text())
    nodes = [f"n{i}" for i in range(1, n + 1)]
    assert sorted(d["services"]) == sorted(["control"] + nodes)
    assert d["services"]["control"]["depends_on"] == nodes
    for node in nodes:
        svc = d["services"][node]
        assert svc["hostname"] == node
        assert svc["privileged"] is True
    assert "jepsen" in d["networks"]


def test_gen_compose_rejects_garbage(tmp_path):
    res, _ = _gen(tmp_path, "zero")
    assert res.returncode != 0


def test_checked_in_compose_matches_template(tmp_path):
    """The checked-in file must be exactly what gen-compose emits for
    its node count, so hand edits can't drift from the templates.
    (The count itself is free to vary: `bin/up --nodes 7` regenerates
    the file in place, which is a legitimate state.)"""
    checked_in = yaml.safe_load(
        (DOCKER_DIR / "docker-compose.yml").read_text())
    n = sum(1 for s in checked_in["services"] if s != "control")
    res, out = _gen(tmp_path, n)
    assert res.returncode == 0, res.stderr
    assert yaml.safe_load(out.read_text()) == checked_in
