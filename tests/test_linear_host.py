"""Host linearizability oracle tests on literal histories (these become the
golden corpus for the TPU kernel)."""

from jepsen_tpu import models as m
from jepsen_tpu.checker.linear import analysis_host, linearizable
from jepsen_tpu.history import History


def op(type, f, value, process=0, **kw):
    return {"type": type, "f": f, "value": value, "process": process,
            "time": 0, **kw}


def test_trivial_valid():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 0), op("ok", "read", 1, 0),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is True


def test_trivial_invalid():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 0), op("ok", "read", 2, 0),
    ])
    a = analysis_host(m.cas_register(), hist)
    assert a["valid?"] is False
    assert a["op"]["value"] == 2


def test_concurrent_read_during_write_either_value_ok():
    # read overlaps the write: may see old or new
    for seen in (None, 1):
        hist = History([
            op("invoke", "write", 0, 0), op("ok", "write", 0, 0),
            op("invoke", "write", 1, 0),
            op("invoke", "read", None, 1),
            op("ok", "read", seen if seen is not None else 0, 1),
            op("ok", "write", 1, 0),
        ])
        assert analysis_host(m.cas_register(), hist)["valid?"] is True


def test_read_after_write_completes_must_see_it():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 1), op("ok", "read", None, 1),
    ])
    # read value None matches anything: valid
    assert analysis_host(m.cas_register(), hist)["valid?"] is True
    hist2 = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 0), op("ok", "write", 2, 0),
        op("invoke", "read", 1, 1), op("ok", "read", 1, 1),
    ])
    assert analysis_host(m.cas_register(), hist2)["valid?"] is False


def test_crashed_write_may_take_effect():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("info", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 2, 2),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is True


def test_crashed_write_may_never_take_effect():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("info", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 1, 2),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is True


def test_failed_op_must_not_take_effect():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "write", 2, 1), op("fail", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 2, 2),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is False


def test_cas_semantics():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "cas", (1, 3), 1), op("ok", "cas", (1, 3), 1),
        op("invoke", "read", None, 0), op("ok", "read", 3, 0),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is True
    bad = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "cas", (2, 3), 1), op("ok", "cas", (2, 3), 1),
    ])
    assert analysis_host(m.cas_register(), bad)["valid?"] is False


def test_mutex():
    good = History([
        op("invoke", "acquire", None, 0), op("ok", "acquire", None, 0),
        op("invoke", "release", None, 0), op("ok", "release", None, 0),
        op("invoke", "acquire", None, 1), op("ok", "acquire", None, 1),
    ])
    assert analysis_host(m.mutex(), good)["valid?"] is True
    bad = History([
        op("invoke", "acquire", None, 0), op("ok", "acquire", None, 0),
        op("invoke", "acquire", None, 1), op("ok", "acquire", None, 1),
    ])
    assert analysis_host(m.mutex(), bad)["valid?"] is False


def test_overlapping_writes_reads_classic():
    # Knossos-style example: two concurrent writes, read sees second
    hist = History([
        op("invoke", "write", 1, 0),
        op("invoke", "write", 2, 1),
        op("ok", "write", 1, 0),
        op("ok", "write", 2, 1),
        op("invoke", "read", None, 2), op("ok", "read", 1, 2),
    ])
    # order w2 then w1 leaves 1: valid
    assert analysis_host(m.cas_register(), hist)["valid?"] is True


def test_checker_interface():
    hist = History([
        op("invoke", "write", 1, 0), op("ok", "write", 1, 0),
        op("invoke", "read", None, 0), op("ok", "read", 1, 0),
    ])
    chk = linearizable({"model": m.cas_register(), "algorithm": "linear"})
    r = chk.check({}, hist, {})
    assert r["valid?"] is True
    assert len(r["configs"]) <= 10


def test_nemesis_ops_ignored():
    hist = History([
        op("invoke", "write", 1, 0),
        op("info", "start-partition", None, "nemesis"),
        op("ok", "write", 1, 0),
    ])
    assert analysis_host(m.cas_register(), hist)["valid?"] is True
