"""Checker tests on literal histories (mirrors the reference's
jepsen/test/jepsen/checker_test.clj strategy)."""

import pytest

from jepsen_tpu import checker as c
from jepsen_tpu import models as m
from jepsen_tpu.history import History


def op(type, f, value, process=0, time=0, **kw):
    return {"type": type, "f": f, "value": value, "process": process,
            "time": time, **kw}


# -- core --------------------------------------------------------------------

def test_merge_valid():
    assert c.merge_valid([]) is True
    assert c.merge_valid([True, True]) is True
    assert c.merge_valid([True, c.UNKNOWN]) == c.UNKNOWN
    assert c.merge_valid([c.UNKNOWN, False]) is False
    with pytest.raises(ValueError):
        c.merge_valid(["nope"])


def test_check_safe_catches():
    def boom(test, hist, opts):
        raise RuntimeError("kaboom")
    r = c.check_safe(boom, {}, History([]), {})
    assert r["valid?"] == c.UNKNOWN and "kaboom" in r["error"]


def test_check_safe_names_the_failing_checker():
    def exploding_checker(test, hist, opts):
        raise ValueError("kaboom")
    r = c.check_safe(exploding_checker, {}, History([]), {})
    assert r["checker"] == "exploding_checker"
    assert "degraded" not in r  # a ValueError isn't a backend failure

    class Exploding(c.Checker):
        def check(self, test, hist, opts):
            raise ValueError("kaboom")

    r = c.check_safe(Exploding(), {}, History([]), {})
    assert r["checker"] == "Exploding"
    # an explicit name (what compose passes) wins
    r = c.check_safe(Exploding(), {}, History([]), {}, name="linear")
    assert r["checker"] == "linear"


def test_check_safe_backend_runtime_error_reports_degraded():
    """Device failures mean the device path fell over, not that the
    history has anomalies — reported as 'degraded' with the
    classifier's fault bucket so operators can tell the two apart.
    jax raises backend-*init* failures as plain RuntimeErrors
    (xla_bridge), so those exact signatures classify too; any other
    plain RuntimeError is a checker bug and must NOT classify, even
    with a device-looking message (tests/test_recovery.py pins the
    full routing)."""
    def device_init_fails(test, hist, opts):
        raise RuntimeError("INTERNAL: failed to initialize TPU system")
    r = c.check_safe(device_init_fails, {}, History([]), {})
    assert r["valid?"] == c.UNKNOWN
    assert r["degraded"] is True
    assert r["checker"] == "device_init_fails"
    assert "initialize TPU" in r["error"]

    def checker_bug(test, hist, opts):
        raise RuntimeError("RESOURCE_EXHAUSTED: ran out of list items")
    r = c.check_safe(checker_bug, {}, History([]), {})
    assert r["valid?"] == c.UNKNOWN
    assert "degraded" not in r


def test_compose_attributes_failures_per_checker():
    def bad(test, hist, opts):
        raise ValueError("which checker was it?")
    good = lambda t, h, o: {"valid?": True}          # noqa: E731
    r = c.compose({"fine": good, "broken": bad})({}, History([]))
    assert r["valid?"] == c.UNKNOWN
    assert r["broken"]["checker"] == "broken"
    assert r["fine"]["valid?"] is True


def test_compose():
    good = lambda t, h, o: {"valid?": True}          # noqa: E731
    bad = lambda t, h, o: {"valid?": False}          # noqa: E731
    r = c.compose({"a": good, "b": bad}).check({}, History([]), {})
    assert r["valid?"] is False
    assert r["a"]["valid?"] is True and r["b"]["valid?"] is False


def test_concurrency_limit():
    inner = lambda t, h, o: {"valid?": True}         # noqa: E731
    r = c.concurrency_limit(2, inner).check({}, History([]), {})
    assert r["valid?"] is True


def test_noop_and_optimism():
    assert c.noop().check({}, History([]), {}) is None
    assert c.unbridled_optimism().check({}, History([]), {})["valid?"]


# -- stats -------------------------------------------------------------------

def test_stats():
    hist = History([
        op("invoke", "read", None), op("ok", "read", 1),
        op("invoke", "write", 2), op("fail", "write", 2),
        op("invoke", "write", 3), op("ok", "write", 3),
    ])
    r = c.stats().check({}, hist, {})
    assert r["valid?"] is True
    assert r["ok-count"] == 2 and r["fail-count"] == 1
    assert r["by-f"]["read"]["ok-count"] == 1


def test_stats_invalid_when_f_never_ok():
    hist = History([op("invoke", "write", 2), op("fail", "write", 2)])
    r = c.stats().check({}, hist, {})
    assert r["valid?"] is False


# -- unhandled exceptions ------------------------------------------------------

def test_unhandled_exceptions():
    hist = History([
        op("info", "read", None, exception={"class": "TimeoutError",
                                            "message": "hi"}),
        op("info", "read", None, exception={"class": "TimeoutError",
                                            "message": "again"}),
        op("info", "write", 2, exception={"class": "IOError",
                                          "message": "x"}),
    ])
    r = c.unhandled_exceptions().check({}, hist, {})
    assert r["valid?"] is True
    assert r["exceptions"][0]["class"] == "TimeoutError"
    assert r["exceptions"][0]["count"] == 2


# -- set ----------------------------------------------------------------------

def test_set_checker_ok():
    hist = History([
        op("invoke", "add", 0), op("ok", "add", 0),
        op("invoke", "add", 1), op("info", "add", 1),
        op("invoke", "read", None), op("ok", "read", [0, 1]),
    ])
    r = c.set_checker().check({}, hist, {})
    assert r["valid?"] is True
    assert r["recovered-count"] == 1  # element 1's add crashed but appeared


def test_set_checker_lost_and_unexpected():
    hist = History([
        op("invoke", "add", 0), op("ok", "add", 0),
        op("invoke", "read", None), op("ok", "read", [9]),
    ])
    r = c.set_checker().check({}, hist, {})
    assert r["valid?"] is False
    assert r["lost-count"] == 1 and r["unexpected-count"] == 1


def test_set_checker_never_read():
    r = c.set_checker().check({}, History([op("invoke", "add", 0)]), {})
    assert r["valid?"] == c.UNKNOWN


# -- set-full -------------------------------------------------------------------

def test_set_full_stable():
    hist = History([
        op("invoke", "add", 0, process=0, time=0),
        op("ok", "add", 0, process=0, time=10),
        op("invoke", "read", None, process=1, time=20),
        op("ok", "read", [0], process=1, time=30),
    ])
    r = c.set_full().check({}, hist, {})
    assert r["valid?"] is True
    assert r["stable-count"] == 1 and r["lost-count"] == 0


def test_set_full_lost():
    hist = History([
        op("invoke", "add", 0, process=0, time=0),
        op("ok", "add", 0, process=0, time=10),
        op("invoke", "read", None, process=1, time=20),
        op("ok", "read", [0], process=1, time=30),
        op("invoke", "read", None, process=1, time=40),
        op("ok", "read", [], process=1, time=50),
    ])
    r = c.set_full().check({}, hist, {})
    assert r["valid?"] is False
    assert r["lost"] == [0]


def test_set_full_never_read():
    hist = History([
        op("invoke", "add", 0, process=0, time=0),
        op("ok", "add", 0, process=0, time=10),
    ])
    r = c.set_full().check({}, hist, {})
    assert r["valid?"] == c.UNKNOWN
    assert r["never-read"] == [0]


def test_set_full_absent_read_concurrent_with_add_is_not_lost():
    # the read missing element 0 is concurrent with its add: never-read,
    # not lost (reference checker.clj:363-381 asymmetry)
    hist = History([
        op("invoke", "read", None, process=1, time=0),
        op("invoke", "add", 0, process=0, time=1),
        op("ok", "add", 0, process=0, time=10),
        op("ok", "read", [], process=1, time=11),
    ])
    r = c.set_full().check({}, hist, {})
    assert r["lost-count"] == 0


def test_set_full_duplicates():
    hist = History([
        op("invoke", "add", 0, process=0, time=0),
        op("ok", "add", 0, process=0, time=1),
        op("invoke", "read", None, process=1, time=2),
        op("ok", "read", [0, 0], process=1, time=3),
    ])
    r = c.set_full().check({}, hist, {})
    assert r["valid?"] is False
    assert r["duplicated"] == {0: 2}


# -- queues ---------------------------------------------------------------------

def test_queue_checker():
    hist = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "dequeue", None, process=1),
        op("ok", "dequeue", 1, process=1),
    ])
    r = c.queue(m.unordered_queue()).check({}, hist, {})
    assert r["valid?"] is True


def test_queue_checker_phantom_dequeue():
    hist = History([
        op("invoke", "dequeue", None, process=1),
        op("ok", "dequeue", 9, process=1),
    ])
    r = c.queue(m.unordered_queue()).check({}, hist, {})
    assert r["valid?"] is False


def test_total_queue():
    hist = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "enqueue", 2, process=0),
        op("info", "enqueue", 2, process=0),
        op("invoke", "drain", None, process=1),
        op("ok", "drain", [1, 2], process=1),
    ])
    r = c.total_queue().check({}, hist, {})
    assert r["valid?"] is True
    assert r["recovered-count"] == 1


def test_total_queue_lost_and_unexpected():
    hist = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "dequeue", None, process=1),
        op("ok", "dequeue", 99, process=1),
    ])
    r = c.total_queue().check({}, hist, {})
    assert r["valid?"] is False
    assert r["lost-count"] == 1 and r["unexpected-count"] == 1


def test_total_queue_indeterminate_dequeue_absorbs_loss():
    """A :info dequeue may have destructively consumed the missing
    message (destructive get, lost response): the verdict degrades to
    unknown, not a false 'lost'."""
    hist = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "dequeue", None, process=1),
        op("info", "dequeue", None, process=1),
    ])
    r = c.total_queue().check({}, hist, {})
    assert r["valid?"] == "unknown"
    assert r["lost-count"] == 1

    # two losses, one indeterminate dequeue: still definitely lost one
    hist2 = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "enqueue", 2, process=0),
        op("ok", "enqueue", 2, process=0),
        op("invoke", "dequeue", None, process=1),
        op("info", "dequeue", None, process=1),
    ])
    assert c.total_queue().check({}, hist2, {})["valid?"] is False

    # a crashed drain absorbs any number of losses
    hist3 = History([
        op("invoke", "enqueue", 1, process=0),
        op("ok", "enqueue", 1, process=0),
        op("invoke", "enqueue", 2, process=0),
        op("ok", "enqueue", 2, process=0),
        op("invoke", "drain", None, process=1),
        op("info", "drain", None, process=1),
    ])
    assert c.total_queue().check({}, hist3, {})["valid?"] == "unknown"


# -- unique ids -------------------------------------------------------------------

def test_unique_ids():
    hist = History([
        op("invoke", "generate", None), op("ok", "generate", 1),
        op("invoke", "generate", None), op("ok", "generate", 2),
    ])
    r = c.unique_ids().check({}, hist, {})
    assert r["valid?"] is True and r["range"] == [1, 2]

    hist2 = History([
        op("invoke", "generate", None), op("ok", "generate", 1),
        op("invoke", "generate", None), op("ok", "generate", 1),
    ])
    r2 = c.unique_ids().check({}, hist2, {})
    assert r2["valid?"] is False and r2["duplicated"] == {1: 2}


# -- counter ---------------------------------------------------------------------

def test_counter_valid():
    hist = History([
        op("invoke", "add", 1, process=0),
        op("ok", "add", 1, process=0),
        op("invoke", "read", None, process=1),
        op("ok", "read", 1, process=1),
    ])
    r = c.counter().check({}, hist, {})
    assert r["valid?"] is True
    assert r["reads"] == [[1, 1, 1]]


def test_counter_pending_add_widens_bounds():
    hist = History([
        op("invoke", "add", 1, process=0),
        op("info", "add", 1, process=0),      # maybe applied
        op("invoke", "read", None, process=1),
        op("ok", "read", 1, process=1),       # saw it: fine
        op("invoke", "read", None, process=2),
        op("ok", "read", 0, process=2),       # didn't: also fine
    ])
    r = c.counter().check({}, hist, {})
    assert r["valid?"] is True


def test_counter_invalid():
    hist = History([
        op("invoke", "add", 1, process=0),
        op("ok", "add", 1, process=0),
        op("invoke", "read", None, process=1),
        op("ok", "read", 5, process=1),
    ])
    r = c.counter().check({}, hist, {})
    assert r["valid?"] is False
    assert r["errors"] == [[1, 5, 1]]


def test_counter_failed_add_not_applied():
    hist = History([
        op("invoke", "add", 1, process=0),
        op("fail", "add", 1, process=0),
        op("invoke", "read", None, process=1),
        op("ok", "read", 0, process=1),
    ])
    r = c.counter().check({}, hist, {})
    assert r["valid?"] is True


# -- log file pattern --------------------------------------------------------------

def test_log_file_pattern(tmp_path):
    test = {"name": "t", "start-time": "now", "store-dir": str(tmp_path),
            "nodes": ["n1", "n2"]}
    d = tmp_path / "t" / "now" / "n1"
    d.mkdir(parents=True)
    (d / "db.log").write_text("all fine\npanic: invariant violation\n")
    r = c.log_file_pattern(r"panic: \w+", "db.log").check(test, History([]),
                                                          {})
    assert r["valid?"] is False and r["count"] == 1
    assert r["matches"][0]["node"] == "n1"


def test_counter_plot_renders_bounds_and_reads(tmp_path):
    hist = History([
        op("invoke", "add", 2, process=0, time=0),
        op("ok", "add", 2, process=0, time=1_000_000_000),
        op("invoke", "read", None, process=1, time=2_000_000_000),
        op("ok", "read", 2, process=1, time=3_000_000_000),
        op("invoke", "read", None, process=1, time=4_000_000_000),
        op("ok", "read", 99, process=1, time=5_000_000_000),  # phantom
    ])
    test = {"name": "counter-plot", "start-time": "t1",
            "store-dir": str(tmp_path)}
    r = c.counter_plot().check(test, hist, {})
    assert r["valid?"] is True  # plots render, they don't judge
    svg_path = tmp_path / "counter-plot" / "t1" / "counter.svg"
    svg = svg_path.read_text()
    assert "lower bound" in svg and "upper bound" in svg
    assert "read out of bounds" in svg


def test_counter_plot_ignores_failed_adds(tmp_path):
    """A failed add definitely did not happen: the plot's upper bound
    must match counter()'s semantics, which drop the pair."""
    hist = History([
        op("invoke", "add", 2, process=0, time=0),
        op("fail", "add", 2, process=0, time=1_000_000_000),
        op("invoke", "read", None, process=1, time=2_000_000_000),
        op("ok", "read", 2, process=1, time=3_000_000_000),
    ])
    test = {"name": "counter-plot-fail", "start-time": "t1",
            "store-dir": str(tmp_path)}
    assert c.counter().check({}, hist, {})["valid?"] is False
    c.counter_plot().check(test, hist, {})
    svg = (tmp_path / "counter-plot-fail" / "t1" /
           "counter.svg").read_text()
    # the read of 2 must render as out of bounds (upper stayed 0)
    assert "read out of bounds" in svg
    assert "upper bound" not in svg  # no surviving add invokes
