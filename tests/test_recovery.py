"""Device-fault recovery: the checkers' recovery ladders.

The contract under test (checker/wgl.py, checker/streaming.py,
_platform.py): a classified backend fault — OOM, device loss,
compile failure, a wedged sync — mid-check yields a *resumed verdict*
identical to an uninterrupted run's, carrying a 'recovered' trail,
instead of the old terminal {'valid?': unknown, 'degraded': True}.
Faults are injected deterministically via _platform.fault_hook /
JEPSEN_TPU_FAULT_INJECT; no hardware is involved.

Shapes are shared with tests/test_streaming.py (chunk 128, 8 slots,
seed-13 histories that fit 8 slots without a rebuild) so tier-1 pays
each kernel compile once.
"""

from __future__ import annotations

import numpy as np
import pytest

import jepsen_tpu._platform as plat
from jepsen_tpu import models
from jepsen_tpu.checker import (Checker, Compose, UNKNOWN, check_safe,
                                linear, streaming, synth, wgl)
import jepsen_tpu.control.retry as retry

MODEL = models.cas_register()
CHUNK = 128
SLOTS = 8   # seed-13 histories need 6 slots: no mid-stream rebuild,
            # so carry checkpoints survive to the injected fault


@pytest.fixture(autouse=True)
def _fast_deterministic_faults(monkeypatch):
    """Zero the recovery backoff (the ladders sleep between retries in
    production) and isolate each test's injection schedule."""
    monkeypatch.setattr(retry, "backoff",
                        lambda *a, **k: iter([0.0] * 1000))
    plat.reset_fault_injection()
    yield
    plat.fault_hook = None
    plat.reset_fault_injection()


def _hist(seed=13, n=400, conc=4):
    return synth.register_history(n, concurrency=conc, values=5,
                                  seed=seed)


def _one_shot(kind, site, at=1):
    """fault_hook raising InjectedFault(kind) at the at-th dispatch on
    site, once — a transient fault, like a real one."""
    state = {"n": 0}

    def hook(s):
        if s == site:
            state["n"] += 1
            if state["n"] == at:
                raise plat.InjectedFault(kind, s, state["n"])
    return hook


def _always(kind, site):
    """fault_hook raising on every dispatch on site — a dead backend."""
    def hook(s):
        if s == site:
            raise plat.InjectedFault(kind, s, 0)
    return hook


# -- classify_backend_error -------------------------------------------------

@pytest.mark.parametrize("msg,bucket", [
    ("RESOURCE_EXHAUSTED: out of memory allocating 2g", "oom"),
    ("INTERNAL: failed to allocate device buffer", "oom"),
    ("UNAVAILABLE: device lost, preempted by scheduler", "device-lost"),
    ("INTERNAL: Mosaic lowering failed", "compile"),
    ("DEADLINE_EXCEEDED: collective timed out", "wedged"),
    ("INTERNAL: something opaque", "wedged"),   # xla but unmatched
])
def test_classifier_buckets_xla_errors(msg, bucket):
    from jaxlib.xla_extension import XlaRuntimeError
    assert plat.classify_backend_error(XlaRuntimeError(msg)) == bucket


def test_classifier_rejects_ordinary_exceptions():
    # a checker bug raised as RuntimeError must NOT classify — even
    # with an OOM-looking message — or recovery would mask real bugs
    assert plat.classify_backend_error(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")) is None
    assert plat.classify_backend_error(ValueError("oom")) is None


def test_classifier_accepts_plain_backend_init_failures():
    # ...except backend-init failures, which jax's xla_bridge raises
    # as PLAIN RuntimeErrors — unambiguously the device falling over
    assert plat.classify_backend_error(RuntimeError(
        "Unable to initialize backend 'tpu': UNAVAILABLE")) \
        == plat.FAULT_DEVICE_LOST
    assert plat.classify_backend_error(RuntimeError(
        "INTERNAL: Failed to initialize TPU system")) \
        == plat.FAULT_DEVICE_LOST
    # subclasses don't get the carve-out (they aren't xla_bridge's)
    class MyError(RuntimeError):
        pass
    assert plat.classify_backend_error(MyError(
        "unable to initialize backend")) is None


def test_classifier_recognizes_module_fault_types():
    for kind in plat.FAULT_KINDS:
        e = plat.InjectedFault(kind, "t", 1)
        assert plat.classify_backend_error(e) == kind
    assert plat.classify_backend_error(
        plat.WedgedDeviceSync("blocked")) == plat.FAULT_WEDGED


# -- the injection shim -----------------------------------------------------

def test_env_spec_fires_once_at_nth_dispatch(monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "oom@here:2")
    plat.maybe_inject_fault("here")           # dispatch 1: clean
    plat.maybe_inject_fault("elsewhere")      # other site: never
    with pytest.raises(plat.InjectedFault) as ei:
        plat.maybe_inject_fault("here")       # dispatch 2: fires
    assert ei.value.kind == "oom"
    plat.maybe_inject_fault("here")           # dispatch 3: spent


def test_env_spec_default_seq_and_reset(monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "device-lost@s")
    with pytest.raises(plat.InjectedFault):
        plat.maybe_inject_fault("s")          # :n defaults to 1
    plat.maybe_inject_fault("s")
    plat.reset_fault_injection()
    with pytest.raises(plat.InjectedFault):
        plat.maybe_inject_fault("s")          # counters rewound


# -- the watchdog -----------------------------------------------------------

def test_wedged_sync_watchdog(monkeypatch):
    import time

    import jax
    monkeypatch.setattr(jax, "device_get",
                        lambda x: time.sleep(30) or x)
    with pytest.raises(plat.WedgedDeviceSync) as ei:
        plat.guarded_device_get(1, deadline_s=0.05, site="test sync")
    assert plat.classify_backend_error(ei.value) == plat.FAULT_WEDGED


def test_watchdog_disabled_without_deadline(monkeypatch):
    monkeypatch.delenv(plat.SYNC_DEADLINE_ENV, raising=False)
    assert plat.guarded_device_get(np.int32(7)) == 7


# -- offline entry: analysis_tpu --------------------------------------------

@pytest.fixture(scope="module")
def offline_baseline():
    return wgl.analysis_tpu(MODEL, _hist())


@pytest.mark.parametrize("kind", plat.FAULT_KINDS)
def test_offline_fault_recovers_with_identical_verdict(
        kind, offline_baseline):
    plat.fault_hook = _one_shot(kind, "offline")
    a = wgl.analysis_tpu(MODEL, _hist())
    assert a["valid?"] == offline_baseline["valid?"] is True
    assert a["recovered"] == {"faults": [kind], "retries": 1}
    assert not a.get("degraded")


def test_offline_exhausted_budget_decides_on_host(offline_baseline):
    plat.fault_hook = _always("device-lost", "offline")
    a = wgl.analysis_tpu(MODEL, _hist(), max_recovery_retries=1)
    assert a["valid?"] == offline_baseline["valid?"] is True
    assert a["recovered"]["fallback"] == "host"
    assert a["recovered"]["faults"] == ["device-lost"] * 2
    assert "host" in a["analyzer"]


def test_offline_exhausted_budget_over_host_cap_degrades(monkeypatch):
    monkeypatch.setattr(wgl, "HOST_FALLBACK_MAX_OPS", 0)
    plat.fault_hook = _always("wedged", "offline")
    a = wgl.analysis_tpu(MODEL, _hist(), max_recovery_retries=1)
    assert a["valid?"] is UNKNOWN
    assert a["degraded"] is True
    assert a["recovery-failed"]["faults"] == ["wedged"] * 2


def test_offline_checker_bug_is_not_absorbed():
    # a plain RuntimeError from inside the entry must escape the
    # ladder untouched (classify returns None)
    def hook(site):
        if site == "offline":
            raise RuntimeError("a checker bug, not a device fault")
    plat.fault_hook = hook
    with pytest.raises(RuntimeError, match="checker bug"):
        wgl.analysis_tpu(MODEL, _hist())


def test_offline_env_knob_end_to_end(monkeypatch):
    monkeypatch.setenv(plat.FAULT_INJECT_ENV, "oom@offline:1")
    a = wgl.analysis_tpu(MODEL, _hist())
    assert a["valid?"] is True
    assert a["recovered"]["faults"] == ["oom"]


# -- batch + sharded entries ------------------------------------------------

BATCH_SEEDS = (10, 11, 12, 13)


def _batch_hists():
    return [_hist(seed=s, n=120, conc=3) for s in BATCH_SEEDS]


@pytest.fixture(scope="module")
def batch_baseline():
    return [r["valid?"] for r in
            wgl.analysis_tpu_batch(MODEL, _batch_hists())]


@pytest.mark.parametrize("kind", plat.FAULT_KINDS)
def test_batch_fault_recovers_with_identical_verdicts(
        kind, batch_baseline):
    plat.fault_hook = _one_shot(kind, "batch")
    rs = wgl.analysis_tpu_batch(MODEL, _batch_hists())
    assert [r["valid?"] for r in rs] == batch_baseline
    assert any(r.get("recovered") for r in rs)
    assert not any(r.get("degraded") for r in rs)


@pytest.fixture(scope="module")
def sharded_baseline():
    ok, pk = wgl.check_batch_sharded(MODEL, _batch_hists())
    return ok, pk


@pytest.mark.parametrize("kind", plat.FAULT_KINDS)
def test_sharded_fault_recovers_with_identical_verdicts(
        kind, sharded_baseline):
    ok0, pk0 = sharded_baseline
    plat.fault_hook = _one_shot(kind, "sharded")
    ok, pk, info = wgl.check_batch_sharded(MODEL, _batch_hists(),
                                           return_info=True)
    assert ok == ok0 and (pk == pk0).all()
    rec = info["recovered"]
    assert rec["faults"][0] == kind
    if kind == plat.FAULT_OOM:
        # the OOM rung splits the key batch and recovers each half
        assert rec["split"] is True


def test_sharded_undecided_keys_are_not_fabricated_anomalies(monkeypatch):
    # every entry faults forever AND the host mirror is capped out:
    # the fallback cannot decide any key. per_key False then means
    # 'unverified' — the info must say so, not claim recovery
    monkeypatch.setattr(wgl, "HOST_FALLBACK_MAX_OPS", 0)

    def hook(site):
        if site in ("sharded", "batch"):
            raise plat.InjectedFault("wedged", site, 0)
    plat.fault_hook = hook
    ok, pk, info = wgl.check_batch_sharded(
        MODEL, _batch_hists(), return_info=True,
        max_recovery_retries=0)
    assert ok is False and not pk.any()
    assert info["degraded"] is True
    assert info["unknown-keys"] == list(range(len(pk)))
    assert "recovered" not in info
    assert info["recovery-failed"]["faults"] == ["wedged"]


def test_sharded_exhausted_budget_falls_back_to_batch(sharded_baseline):
    ok0, pk0 = sharded_baseline
    plat.fault_hook = _always("device-lost", "sharded")
    ok, pk, info = wgl.check_batch_sharded(
        MODEL, _batch_hists(), return_info=True,
        max_recovery_retries=0)
    assert ok == ok0 and (pk == pk0).all()
    assert info["recovered"]["fallback"] == "batch"


# -- streaming: checkpointed carry + resume ---------------------------------

def _stream(hist, family, hook=None, checkpoint_every=2, **kw):
    plat.fault_hook = hook
    try:
        s = streaming.WglStream(
            MODEL, chunk_entries=CHUNK, slots=SLOTS,
            checkpoint_every=checkpoint_every, engine=family,
            state_range=(-1, 4) if family == "dense" else None, **kw)
        for op in hist.ops:
            s.feed(op)
        return s, s.finish()
    finally:
        plat.fault_hook = None


def _stream_bytes(s):
    return (np.concatenate(s._steps_log) if s._steps_log
            else np.zeros((0, 1), np.int32))


@pytest.fixture(scope="module")
def stream_baselines():
    # computed once per family; the fault runs below must match these
    out = {}
    for family in ("sort", "dense"):
        plat.reset_fault_injection()
        s, r = _stream(_hist(), family)
        out[family] = (r, _stream_bytes(s))
    return out


@pytest.mark.parametrize("family", ["sort", "dense"])
@pytest.mark.parametrize("kind", plat.FAULT_KINDS)
def test_stream_mid_chunk_fault_resumes_identically(
        kind, family, stream_baselines):
    """The acceptance matrix: a fault killed at chunk 3 (checkpoint
    cadence 2) resumes from the chunk-2 carry checkpoint and produces
    a byte-identical step stream and identical verdict."""
    r0, bytes0 = stream_baselines[family]
    s, r = _stream(_hist(), family,
                   hook=_one_shot(kind, "stream-chunk", at=3))
    assert r["valid?"] == r0["valid?"] is True
    assert r["op-count"] == r0["op-count"]
    rec = r["recovered"]
    assert rec["faults"] == [kind] and rec["retries"] == 1
    b = _stream_bytes(s)
    assert b.shape == bytes0.shape and (b == bytes0).all()
    if family == "dense" and kind == plat.FAULT_OOM:
        # dense OOM re-selects onto the sort family; its checkpoint
        # cannot seed a sort carry, so the resume replays cold
        assert rec["resumed-from-chunk"] == 0
        assert "dense" not in r["analyzer"]
    else:
        assert rec["resumed-from-chunk"] == 2


@pytest.mark.parametrize("family", ["sort", "dense"])
def test_stream_fault_preserves_blame_certificate(family):
    bad = synth.corrupt(_hist(), seed=3)
    s0, r0 = _stream(bad, family)
    s1, r1 = _stream(bad, family,
                     hook=_one_shot("device-lost", "stream-chunk",
                                    at=3))
    assert r0["valid?"] is False and r1["valid?"] is False
    assert r1["op-index"] == r0["op-index"]
    assert r1["op"] == r0["op"]
    b0, b1 = _stream_bytes(s0), _stream_bytes(s1)
    assert b0.shape == b1.shape and (b0 == b1).all()


def test_stream_oom_backpressure_halves_chunk():
    s, r = _stream(_hist(), "sort",
                   hook=_one_shot("oom", "stream-chunk", at=3))
    assert s.chunk == CHUNK // 2
    assert r["valid?"] is True


def test_stream_exhausted_budget_disables_stream():
    # past the budget the stream reports None: core.run's offline
    # re-check path (whose own ladder ends at the host mirror) covers
    attempts = {"n": 0}
    dead = _always("device-lost", "stream-chunk")

    def hook(site):
        if site == "stream-chunk":
            attempts["n"] += 1
        dead(site)

    s, r = _stream(_hist(), "sort", hook=hook, max_recovery_retries=1)
    assert r is None
    assert s._failed is not None
    # once the budget is spent the drain stops: the initial dispatch
    # plus one retry, never one attempt per remaining tail chunk
    # against the dead backend
    assert attempts["n"] == 2


def test_stream_checkpoint_disabled_replays_cold():
    s, r = _stream(_hist(), "sort", checkpoint_every=0,
                   hook=_one_shot("wedged", "stream-chunk", at=3))
    assert r["valid?"] is True
    assert r["recovered"]["resumed-from-chunk"] == 0


# -- check_safe / Compose routing -------------------------------------------

class _Raises(Checker):
    def __init__(self, exc):
        self.exc = exc

    def check(self, test, hist, opts):
        raise self.exc


def test_check_safe_reports_classified_fault_as_degraded():
    r = check_safe(_Raises(plat.InjectedFault("oom", "t", 1)), {}, [])
    assert r["valid?"] is UNKNOWN
    assert r["degraded"] is True and r["fault"] == "oom"


def test_check_safe_plain_runtime_error_is_not_degraded():
    r = check_safe(_Raises(RuntimeError("bug")), {}, [])
    assert r["valid?"] is UNKNOWN
    assert "degraded" not in r and "fault" not in r


class _Returns(Checker):
    def __init__(self, result):
        self.result = result

    def check(self, test, hist, opts):
        return dict(self.result)


def test_compose_surfaces_recovery_vs_degradation():
    r = Compose({
        "fine": _Returns({"valid?": True}),
        "healed": _Returns({"valid?": True,
                            "recovered": {"faults": ["oom"],
                                          "retries": 1}}),
        "lost": _Returns({"valid?": UNKNOWN, "degraded": True}),
    }).check({}, [], {})
    assert r["recovered-checkers"] == ["healed"]
    assert r["degraded-checkers"] == ["lost"]


def test_linearizable_threads_retry_budget_from_test_map():
    plat.fault_hook = _always("device-lost", "offline")
    c = linear.Linearizable(MODEL)
    r = c.check({"max-recovery-retries": 0}, _hist(n=100), {})
    assert r["valid?"] is True
    assert r["recovered"]["fallback"] == "host"


# -- OnlineChecker driver crash ---------------------------------------------

def test_online_driver_crash_degrades_streamed_results():
    class _Target:
        violation = False

        def feed(self, op):
            pass

        def finish(self):
            return {"valid?": True}

    oc = streaming.OnlineChecker({"lin": _Target()})
    oc.offer("not-an-op")   # AttributeError inside the driver thread
    out = oc.finalize(timeout_s=30.0)
    assert out["degraded"] is True
    assert "AttributeError" in out["error"]
    assert "lin" not in out   # crashed drivers report no verdicts


def test_online_target_crash_is_contained_per_target():
    class _Bad:
        violation = False

        def feed(self, op):
            raise ValueError("encoder bug")

        def finish(self):   # pragma: no cover — dead targets skip it
            return {"valid?": True}

    class _Good:
        violation = False

        def __init__(self):
            self.n = 0

        def feed(self, op):
            self.n += 1

        def finish(self):
            return {"valid?": True, "fed": self.n}

    oc = streaming.OnlineChecker({"bad": _Bad(), "good": _Good()})
    oc.offer({"type": "invoke", "process": 0})
    out = oc.finalize(timeout_s=30.0)
    assert "degraded" not in out      # the driver itself survived
    assert "bad" not in out
    assert out["good"]["fed"] == 1


# -- surfacing: report / web / core -----------------------------------------

def test_report_recovery_line():
    from jepsen_tpu import report
    assert report.recovery_line({}) == ""
    line = report.recovery_line(
        {"recovered": {"faults": ["oom", "wedged"], "retries": 2,
                       "resumed-from-chunk": 4}})
    assert "oom, wedged" in line
    assert "2 retries" in line and "chunk 4" in line


def test_web_recovery_note():
    from jepsen_tpu import web
    assert web.recovery_note({}) == ""
    assert web.recovery_note(
        {"lin": {"valid?": True,
                 "recovered": {"faults": ["oom"]}}}) == " (recovered)"
    # degradation outranks recovery: a missing verdict is the headline
    assert web.recovery_note(
        {"lin": {"recovered": {"faults": ["oom"]}},
         "other": {"degraded": True}}) == " (degraded)"


def test_log_results_distinguishes_recovery_from_degradation(caplog):
    import logging

    from jepsen_tpu import core
    with caplog.at_level(logging.INFO, logger="jepsen_tpu.core"):
        core.log_results({"results": {
            "valid?": True, "recovered-checkers": ["lin"],
            "lin": {"valid?": True,
                    "recovered": {"faults": ["oom"], "retries": 1}}}})
    assert any("recovered from backend faults" in m
               for m in caplog.messages)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="jepsen_tpu.core"):
        core.log_results({"results": {
            "valid?": UNKNOWN, "degraded-checkers": ["lin"]}})
    assert any("DEGRADED" in m for m in caplog.messages)


def test_cli_exposes_max_recovery_retries():
    from jepsen_tpu import cli
    spec = cli.test_opt_spec()
    assert any(s["long"] == "--max-recovery-retries" for s in spec)
