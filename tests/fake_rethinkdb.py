"""In-process fake RethinkDB speaking the V0_4/JSON ReQL subset in
`jepsen_tpu/suites/reql_proto.py`: db/table create, get, get_field
with default, insert with conflict=update, and update with a
branch-on-eq row function (the cas). One consistent store."""

from __future__ import annotations

import json
import socket
import struct
import threading

from jepsen_tpu.suites import reql_proto as r


class FakeRethinkDB:
    def __init__(self):
        self.tables: dict[tuple, dict] = {}   # (db, tbl) -> {id: doc}
        self.lock = threading.Lock()
        # corrupt_hook(term, out) -> replacement out; lets negative
        # tests serve wrong answers without touching the store
        self.corrupt_hook = None
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(32)
        self.port = self.srv.getsockname()[1]
        self.running = True
        threading.Thread(target=self._accept, daemon=True).start()

    def stop(self):
        self.running = False
        try:
            self.srv.close()
        except OSError:
            pass

    def _accept(self):
        while self.running:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            # request/response protocol: Nagle + delayed ACK cost
            # ~40ms per round trip without this
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _read_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn):
        try:
            magic, = struct.unpack("<I", self._read_exact(conn, 4))
            klen, = struct.unpack("<I", self._read_exact(conn, 4))
            if klen:
                self._read_exact(conn, klen)
            self._read_exact(conn, 4)  # protocol magic
            conn.sendall(b"SUCCESS\x00")
            while True:
                token, = struct.unpack("<q", self._read_exact(conn, 8))
                qlen, = struct.unpack("<I", self._read_exact(conn, 4))
                qtype, term, _opts = json.loads(
                    self._read_exact(conn, qlen))
                try:
                    with self.lock:
                        out = self._eval(term, None)
                    if self.corrupt_hook is not None:
                        out = self.corrupt_hook(term, out)
                    resp = {"t": r.R_SUCCESS_ATOM, "r": [out]}
                except _Abort as e:
                    resp = {"t": r.R_RUNTIME_ERROR, "r": [str(e)]}
                body = json.dumps(resp).encode()
                conn.sendall(struct.pack("<q", token)
                             + struct.pack("<I", len(body)) + body)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- term evaluation -----------------------------------------------------

    def _eval(self, term, row):
        if not isinstance(term, list):
            return term
        tid, args = term[0], term[1] if len(term) > 1 else []
        opts = term[2] if len(term) > 2 else {}
        if tid == r.T_DB:
            return ("db", args[0])
        if tid == r.T_DB_CREATE:
            return {"dbs_created": 1}
        if tid == r.T_TABLE_CREATE:
            dbref = self._eval(args[0], row)
            key = (dbref[1], args[1])
            if key in self.tables:
                raise _Abort(f"Table `{args[1]}` already exists")
            self.tables[key] = {}
            return {"tables_created": 1}
        if tid == r.T_TABLE:
            dbref = self._eval(args[0], row)
            return ("table", self.tables.setdefault(
                (dbref[1], args[1]), {}))
        if tid == r.T_WAIT:
            return {"ready": 1}
        if tid == r.T_GET:
            tbl = self._eval(args[0], row)[1]
            return ("doc", tbl, self._eval(args[1], row))
        if tid == r.T_GET_FIELD:
            target = self._eval(args[0], row)
            doc = self._deref(target)
            field = self._eval(args[1], row)
            if doc is None or field not in doc:
                raise _Abort(f"No attribute `{field}`")
            return doc[field]
        if tid == r.T_DEFAULT:
            try:
                return self._eval(args[0], row)
            except _Abort:
                return self._eval(args[1], row)
        if tid == r.T_INSERT:
            tbl = self._eval(args[0], row)[1]
            doc = dict(args[1])
            key = doc["id"]
            if key in tbl and opts.get("conflict") != "update":
                return {"errors": 1, "inserted": 0,
                        "first_error": "Duplicate primary key"}
            if key in tbl:
                tbl[key].update(doc)
                return {"errors": 0, "replaced": 1, "inserted": 0}
            tbl[key] = doc
            return {"errors": 0, "inserted": 1}
        if tid == r.T_UPDATE:
            target = self._eval(args[0], row)
            if isinstance(target, tuple) and target[0] == "table":
                # table-wide update (e.g. the rethinkdb.table_config
                # write-acks reconfiguration): apply to every doc
                n = 0
                for doc in target[1].values():
                    patch = self._apply_func(args[1], doc)
                    doc.update(patch)
                    n += 1
                return {"errors": 0, "replaced": n}
            doc = self._deref(target)
            if doc is None:
                return {"errors": 0, "skipped": 1, "replaced": 0}
            func = args[1]
            try:
                patch = self._apply_func(func, doc)
            except _Abort as e:
                return {"errors": 1, "replaced": 0,
                        "first_error": str(e)}
            changed = any(doc.get(k) != v for k, v in patch.items())
            doc.update(patch)
            return {"errors": 0,
                    "replaced": 1 if changed else 0,
                    "unchanged": 0 if changed else 1}
        if tid == r.T_EQ:
            return self._eval(args[0], row) == self._eval(args[1], row)
        if tid == r.T_BRANCH:
            if self._eval(args[0], row):
                return self._eval(args[1], row)
            return self._eval(args[2], row)
        if tid == r.T_ERROR:
            raise _Abort(self._eval(args[0], row))
        if tid == r.T_VAR:
            return row
        raise _Abort(f"unsupported term {tid}")

    @staticmethod
    def _deref(target):
        if isinstance(target, tuple) and target[0] == "doc":
            return target[1].get(target[2])
        return target

    def _apply_func(self, func, doc):
        """[FUNC, [[MAKE_ARRAY,[1]], body]] applied to doc."""
        if isinstance(func, dict):
            return func
        body = func[1][1]
        out = self._eval(body, doc)
        if not isinstance(out, dict):
            raise _Abort("update function must return an object")
        return out


class _Abort(Exception):
    pass
