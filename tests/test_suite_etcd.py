"""etcd suite tests: DB command generation against the recording dummy
remote, client semantics against an in-process fake etcd gateway, and a
complete hermetic suite run (real HTTP, real checkers)."""

import pytest

from fake_etcd import FakeEtcd

from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import etcd, suite


@pytest.fixture
def fake():
    f = FakeEtcd()
    f.port = f.start()
    yield f
    f.stop()


def url_fn(fake):
    return lambda node: f"http://127.0.0.1:{fake.port}"


def test_suite_registry():
    assert suite("etcd") is etcd


def test_initial_cluster():
    t = {"nodes": ["n1", "n2"]}
    assert etcd.initial_cluster(t) == \
        "n1=http://n1:2380,n2=http://n2:2380"


def test_db_setup_commands():
    """DB setup runs the install + daemon-start pipeline over the
    control layer (tutorial 02-db.md)."""
    log = []
    # scripted ls so install_archive sees one extracted root dir
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "etcd-v3.5.9-linux-amd64"})
    test = {"nodes": ["n1"], "tarball": "file:///tmp/etcd.tgz"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            etcd.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "start-stop-daemon" in cmds
    assert "--initial-cluster n1=http://n1:2380" in cmds
    assert "--data-dir /opt/etcd/data" in cmds
    # teardown kills the daemon and wipes data
    log.clear()
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            etcd.db().teardown(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "rm -rf /opt/etcd/data" in cmds


def test_client_kv_roundtrip(fake):
    c = etcd.EtcdClient(url=f"http://127.0.0.1:{fake.port}")
    assert c.read("k") is None
    c.write("k", 3)
    assert c.read("k") == "3"
    assert c.cas("k", 3, 4) is True
    assert c.cas("k", 3, 5) is False
    assert c.read("k") == "4"


def test_client_invoke_register(fake):
    c = etcd.EtcdClient(url=f"http://127.0.0.1:{fake.port}")
    w = c.invoke({}, {"type": "invoke", "f": "write", "value": 2,
                      "process": 0})
    assert w["type"] == "ok"
    r = c.invoke({}, {"type": "invoke", "f": "read", "value": None,
                      "process": 0})
    assert r["type"] == "ok" and r["value"] == 2
    cas = c.invoke({}, {"type": "invoke", "f": "cas", "value": (2, 3),
                        "process": 0})
    assert cas["type"] == "ok"
    cas2 = c.invoke({}, {"type": "invoke", "f": "cas", "value": (2, 4),
                         "process": 0})
    assert cas2["type"] == "fail"


def test_client_errors_classified():
    # nothing listening on this port: connection refused → definite fail
    c = etcd.EtcdClient(timeout_s=0.2, url="http://127.0.0.1:1")
    r = c.invoke({}, {"type": "invoke", "f": "read", "value": None,
                      "process": 0})
    assert r["type"] == "fail"
    w = c.invoke({}, {"type": "invoke", "f": "write", "value": 1,
                      "process": 0})
    assert w["type"] in ("fail", "info")


def test_etcd_test_map_builds():
    t = etcd.etcd_test({"nodes": ["n1", "n2", "n3"], "concurrency": 6,
                        "ssh": {"dummy": True}, "workload": "register",
                        "time-limit": 5})
    assert t["name"] == "etcd-register"
    assert t["db"].version == etcd.DEFAULT_VERSION
    assert t["generator"] is not None
    assert t["concurrency"] == 6


@pytest.mark.parametrize("workload", sorted(etcd.WORKLOADS))
def test_hermetic_suite_run(tmp_path, fake, workload):
    """The whole suite end to end: dummy remote for the cluster, fake
    etcd over real HTTP for the data plane, full checker stack."""
    opts = {
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 6,
        "ssh": {"dummy": True},
        "workload": workload,
        "rate": 200,
        "time-limit": 3,
        "ops-per-key": 20,
        "nemesis": "none",
        "store-dir": str(tmp_path / "store"),
    }
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = etcd.etcd_test(opts)
    t["db"] = jepsen_tpu.db.noop    # no real cluster
    t["os"] = jepsen_tpu.os_.noop
    t["client-url-fn"] = url_fn(fake)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    assert len(done["history"]) > 10
