"""Elle-class cycle checker tests: kernels, list-append, rw-register —
golden histories in, verdicts out (the reference's checker test style)."""

import numpy as np

import jepsen_tpu.generator as gen
from jepsen_tpu.checker import elle
from jepsen_tpu.checker.elle import kernels, list_append, wr
from jepsen_tpu.generator import simulate as sim
from jepsen_tpu.history import history


# -- kernels ----------------------------------------------------------------

def test_transitive_closure():
    a = np.zeros((3, 3), bool)
    a[0, 1] = a[1, 2] = True
    c = kernels.transitive_closure(a)
    assert c[0, 2] and c[0, 1] and c[1, 2]
    assert not c[2, 0] and not c.diagonal().any()


def test_transitive_closure_sharded():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("rows",))
    a = np.zeros((10, 10), bool)
    for i in range(9):
        a[i, i + 1] = True
    c = kernels.transitive_closure(a, mesh=mesh)
    assert c[0, 9]
    assert not c.diagonal().any()


def test_analyze_graph_g0():
    n = 2
    ww = np.zeros((n, n), bool)
    ww[0, 1] = ww[1, 0] = True
    r = kernels.analyze_graph(ww, np.zeros_like(ww), np.zeros_like(ww))
    assert r["G0"] and r["G1c"]
    assert not r["G2-item"]


def test_analyze_graph_g_single():
    n = 2
    ww = np.zeros((n, n), bool)
    wr_m = np.zeros((n, n), bool)
    rw = np.zeros((n, n), bool)
    wr_m[0, 1] = True
    rw[1, 0] = True
    r = kernels.analyze_graph(ww, wr_m, rw)
    assert not r["G0"] and not r["G1c"]
    assert r["G-single"] and not r["G2-item"]


def test_analyze_graph_g2():
    # two rw edges forming the only cycle
    n = 2
    rw = np.zeros((n, n), bool)
    rw[0, 1] = rw[1, 0] = True
    r = kernels.analyze_graph(np.zeros_like(rw), np.zeros_like(rw), rw)
    assert not r["G1c"] and not r["G-single"]
    assert r["G2-item"]


def test_analyze_graph_acyclic():
    n = 3
    ww = np.zeros((n, n), bool)
    ww[0, 1] = ww[1, 2] = True
    r = kernels.analyze_graph(ww, np.zeros_like(ww), np.zeros_like(ww))
    assert not any(r[t] for t in ("G0", "G1c", "G-single", "G2-item"))


# -- list append ------------------------------------------------------------

def _ok(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn, "process": process,
             "time": t},
            {"type": "ok", "f": "txn", "value": txn, "process": process,
             "time": t + 1}]


def _fail(process, txn, t):
    return [{"type": "invoke", "f": "txn", "value": txn, "process": process,
             "time": t},
            {"type": "fail", "f": "txn", "value": txn, "process": process,
             "time": t + 1}]


def test_append_valid_history():
    h = history(
        _ok(0, [["append", "x", 1]], 0)
        + _ok(1, [["r", "x", [1]], ["append", "x", 2]], 2)
        + _ok(0, [["r", "x", [1, 2]]], 4))
    res = list_append.check(h)
    assert res["valid?"] is True


def test_append_g1c_write_read_cycle():
    h = history(
        _ok(0, [["append", "x", 1], ["r", "y", [1]]], 0)
        + _ok(1, [["append", "y", 1], ["r", "x", [1]]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]
    cyc = res["anomalies"]["G1c"][0]["cycle"]
    assert cyc is not None and len(cyc) == 3  # T -> T' -> T


def test_append_g_single():
    h = history(
        _ok(0, [["append", "x", 1], ["append", "y", 1]], 0)
        + _ok(1, [["r", "x", [1]], ["r", "y", []]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G-single" in res["anomaly-types"]


def test_append_g0():
    h = history(
        _ok(0, [["append", "x", 1], ["append", "y", 2]], 0)
        + _ok(1, [["append", "x", 2], ["append", "y", 1]], 2)
        + _ok(2, [["r", "x", [1, 2]]], 4)
        + _ok(3, [["r", "y", [1, 2]]], 6))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G0" in res["anomaly-types"]


def test_append_g1a_aborted_read():
    h = history(
        _fail(0, [["append", "x", 1]], 0)
        + _ok(1, [["r", "x", [1]]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G1a" in res["anomaly-types"]


def test_append_g1b_intermediate_read():
    h = history(
        _ok(0, [["append", "x", 1], ["append", "x", 2]], 0)
        + _ok(1, [["r", "x", [1]]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G1b" in res["anomaly-types"]


def test_append_duplicates():
    h = history(
        _ok(0, [["append", "x", 1]], 0)
        + _ok(1, [["append", "x", 1]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "duplicate-elements" in res["anomaly-types"]


def test_append_incompatible_order():
    h = history(
        _ok(0, [["r", "x", [1, 2]]], 0)
        + _ok(1, [["r", "x", [1, 3]]], 2))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "incompatible-order" in res["anomaly-types"]


def test_append_internal():
    h = history(_ok(0, [["append", "x", 5], ["r", "x", []]], 0))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "internal" in res["anomaly-types"]
    # and the consistent version is fine
    h2 = history(_ok(0, [["append", "x", 5], ["r", "x", [5]]], 0))
    assert list_append.check(h2)["valid?"] is True


def test_append_anomaly_selection():
    # a G-single history passes when only G1 is checked
    h = history(
        _ok(0, [["append", "x", 1], ["append", "y", 1]], 0)
        + _ok(1, [["r", "x", [1]], ["r", "y", []]], 2))
    res = list_append.check(h, anomalies=("G1a", "G1b", "G1c"))
    assert res["valid?"] is True


# -- rw register ------------------------------------------------------------

def test_wr_valid_history():
    h = history(
        _ok(0, [["w", "x", 1]], 0)
        + _ok(1, [["r", "x", 1]], 2)
        + _ok(0, [["w", "x", 2]], 4)
        + _ok(1, [["r", "x", 2]], 6))
    res = wr.check(h)
    assert res["valid?"] is True


def test_wr_g1c():
    h = history(
        _ok(0, [["w", "x", 1], ["r", "y", 1]], 0)
        + _ok(1, [["w", "y", 1], ["r", "x", 1]], 2))
    res = wr.check(h)
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]


def test_wr_g_single():
    h = history(
        _ok(0, [["w", "x", 1], ["w", "y", 1]], 0)
        + _ok(1, [["r", "y", 1], ["r", "x", None]], 2))
    res = wr.check(h)
    assert res["valid?"] is False
    assert "G-single" in res["anomaly-types"]


def test_wr_g1a_and_g1b():
    h = history(
        _fail(0, [["w", "x", 9]], 0)
        + _ok(1, [["r", "x", 9]], 2))
    res = wr.check(h)
    assert "G1a" in res["anomaly-types"]

    h2 = history(
        _ok(0, [["w", "x", 1], ["w", "x", 2]], 0)
        + _ok(1, [["r", "x", 1]], 2))
    res2 = wr.check(h2)
    assert "G1b" in res2["anomaly-types"]


def test_wr_internal():
    h = history(_ok(0, [["w", "x", 1], ["r", "x", 2]], 0))
    res = wr.check(h)
    assert "internal" in res["anomaly-types"]


def test_wr_ww_from_intra_txn_order():
    # T1 w x 1; T2 r x 1, w x 2 => ww T1->T2; T1 also reads T2's write:
    # cycle (G1c via ww+wr)
    h = history(
        _ok(0, [["w", "x", 1], ["r", "y", 2]], 0)
        + _ok(1, [["r", "x", 1], ["w", "x", 9], ["w", "y", 2]], 2))
    res = wr.check(h)
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]


# -- generators + workload bundles ------------------------------------------

def test_append_gen_traceable():
    with gen.fixed_rng(2):
        ops = sim.quick(sim.n_plus_nemesis_context(3),
                        gen.clients(gen.limit(40, elle.append_gen())))
    assert len(ops) == 40
    seen = set()
    for o in ops:
        assert o["f"] == "txn"
        for m in o["value"]:
            assert m[0] in ("append", "r")
            if m[0] == "append":
                assert (m[1], m[2]) not in seen  # unique per key
                seen.add((m[1], m[2]))


def _serial_store_executor(mode):
    """A simulate-completion fn applying txns serially to an in-memory
    store (invocation order = serialization order, so the history must
    verify)."""
    store = {}

    def complete(ctx, invoke):
        out = dict(invoke)
        txn = []
        for m in invoke["value"]:
            f, k, v = m
            if f == "append":
                store.setdefault(k, []).append(v)
                txn.append([f, k, v])
            elif f == "w":
                store[k] = v
                txn.append([f, k, v])
            else:  # read
                got = store.get(k, [] if mode == "append" else None)
                txn.append(["r", k, list(got) if mode == "append"
                            else got])
        out["type"] = "ok"
        out["value"] = txn
        out["time"] = invoke["time"] + 1
        return out

    return complete


def test_wr_workload_end_to_end():
    from jepsen_tpu.workloads import wr as ww
    bundle = ww.workload()
    with gen.fixed_rng(6):
        h = sim.simulate(sim.n_plus_nemesis_context(3),
                         gen.clients(gen.limit(30, bundle["generator"])),
                         _serial_store_executor("wr"))
    res = bundle["checker"].check({}, history(h), {})
    assert res["valid?"] is True
    assert res["txn-count"] == 30


def test_append_workload_end_to_end():
    from jepsen_tpu.workloads import append as aw
    bundle = aw.workload({"key-count": 3})
    with gen.fixed_rng(8):
        h = sim.simulate(sim.n_plus_nemesis_context(3),
                         gen.clients(gen.limit(30, bundle["generator"])),
                         _serial_store_executor("append"))
    res = bundle["checker"].check({}, history(h), {})
    assert res["valid?"] is True
    assert res["txn-count"] == 30


def test_append_unfilled_reads_carry_no_information():
    # echo-style histories (reads stay None) must not produce anomalies
    h = history(
        _ok(0, [["append", "x", 1], ["r", "y", None]], 0)
        + _ok(1, [["append", "y", 1], ["r", "x", None]], 2))
    assert list_append.check(h)["valid?"] is True


def test_expand_anomalies():
    assert elle.expand_anomalies(("G1",)) == ("G1a", "G1b", "G1c")
    assert elle.expand_anomalies(("G0", "G2")) == ("G0", "G-single",
                                                   "G2-item")


def test_g2_not_masked_by_unrelated_weaker_cycle():
    # a G1c cycle on a/b AND an independent pure write-skew (2 rw) on x/y;
    # a serializability-only config must still flag the G2 cycle
    h = history(
        _ok(0, [["w", "a", 1], ["r", "b", 1]], 0)
        + _ok(1, [["w", "b", 1], ["r", "a", 1]], 2)
        + _ok(2, [["w", "x", 1], ["r", "y", None]], 4)
        + _ok(3, [["w", "y", 1], ["r", "x", None]], 6))
    res = wr.check(h, anomalies=("G-single", "G2-item"))
    assert res["valid?"] is False
    assert "G2-item" in res["anomaly-types"]
    cert = res["anomalies"]["G2-item"][0]["cycle"]
    assert cert is not None


def test_elle_ignores_nemesis_ops():
    h = history(
        _ok(0, [["append", "x", 1]], 0)
        + [{"type": "info", "f": "start-partition", "value": ["n1", "n2"],
            "process": "nemesis", "time": 1}]
        + _ok(1, [["r", "x", [1]]], 2))
    res = list_append.check(h)
    assert res["valid?"] is True
    assert res["txn-count"] == 2  # nemesis op is not a transaction
    res2 = wr.check(history(
        _ok(0, [["w", "x", 1]], 0)
        + [{"type": "info", "f": "start", "value": [{"a": 1}],
            "process": "nemesis", "time": 1}]
        + _ok(1, [["r", "x", 1]], 2)))
    assert res2["valid?"] is True


# -- sparse SCC pipeline at scale --------------------------------------------

def test_scc_labels_matches_tarjan_fallback():
    rng = np.random.default_rng(3)
    n = 200
    m = 600
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    a = kernels.scc_labels(n, src, dst)
    b = kernels._tarjan_labels(n, src, dst)
    # identical partitions (label ids may differ): bijective label map
    fwd, bwd = {}, {}
    for x, y in zip(a.tolist(), b.tolist()):
        assert fwd.setdefault(x, y) == y
        assert bwd.setdefault(y, x) == x


def test_analyze_edges_valid_at_scale():
    from jepsen_tpu.checker import synth
    h = synth.append_history(5000)
    res = list_append.check(h)
    assert res["valid?"] is True
    assert res["txn-count"] == 5000


def test_analyze_edges_many_injected_sccs():
    from jepsen_tpu.checker import synth
    h = synth.inject_append_cycles(synth.append_history(500), 20, "G1c")
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]
    cert = res["anomalies"]["G1c"][0]["cycle"]
    assert cert is not None and cert[0]["index"] == cert[-1]["index"]


def test_analyze_edges_g_single_injected():
    from jepsen_tpu.checker import synth
    h = synth.inject_append_cycles(synth.append_history(300), 5,
                                   "G-single")
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G-single" in res["anomaly-types"]


def test_analyze_edges_sharded_mesh():
    import jax
    from jax.sharding import Mesh
    from jepsen_tpu.checker import synth

    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))
    h = synth.inject_append_cycles(synth.append_history(300), 11, "G1c")
    res = list_append.check(h, mesh=mesh)
    assert res["valid?"] is False
    assert "G1c" in res["anomaly-types"]


def test_classify_batches_host_parity(monkeypatch):
    # the JEPSEN_TPU_ELLE_HOST=1 fallback (used when the device relay
    # is wedged, bench.py section_config5) must agree flag-for-flag
    # with the device kernel on random SCC blocks — so make sure the
    # "device" side really takes the device path
    monkeypatch.delenv("JEPSEN_TPU_ELLE_HOST", raising=False)
    rng = np.random.default_rng(11)
    buckets = {}
    for e in (8, 16):
        b = 6
        mats = []
        for _ in range(3):
            m = (rng.random((b, e, e)) < 0.15).astype(np.float32)
            for s in range(b):
                np.fill_diagonal(m[s], 0.0)
            mats.append(m)
        buckets[e] = tuple(mats)
    dev = kernels._classify_batches(buckets)
    host = kernels._classify_batches_host(buckets)
    for e in buckets:
        for d, h in zip(dev[e], host[e]):
            assert (np.asarray(d) == np.asarray(h)).all()


def test_check_host_classify_env(monkeypatch):
    from jepsen_tpu.checker import synth
    monkeypatch.setenv("JEPSEN_TPU_ELLE_HOST", "1")
    h = synth.inject_append_cycles(synth.append_history(300), 7, "G1c")
    res = list_append.check(h)
    assert res["valid?"] is False and "G1c" in res["anomaly-types"]


def test_analyze_edges_oversized_scc_host_path():
    # force the oversized path with a tiny max_dense: a 4-node G1c ring
    # plus a disjoint 2-node G0 ring
    edges = {(0, 1): {"ww"}, (1, 2): {"wr"}, (2, 3): {"ww"},
             (3, 0): {"wr"}, (4, 5): {"ww"}, (5, 4): {"ww"}}
    res = kernels.analyze_edges(6, edges, max_dense=3)
    assert res["oversized-sccs"] == 1  # the 4-ring
    assert res["G0"] and res["G1c"]
    assert not res["G-single"] and not res["G2-item"]


def test_analyze_edges_oversized_scc_with_outgoing_edges():
    # an oversized SCC with edges leaving the SCC must still classify
    # (regression: dst-outside-SCC edges crashed the host classifier)
    edges = {(0, 1): {"ww"}, (1, 2): {"ww"}, (2, 0): {"ww"},
             (2, 3): {"ww"}, (3, 4): {"wr"}}
    res = kernels.analyze_edges(5, edges, max_dense=2)
    assert res["G0"] and res["G1c"]


def test_analyze_edges_oversized_g2_not_masked_by_g1c():
    # one SCC containing BOTH a wr-cycle (G1c) and a 2-rw cycle (G2);
    # the oversized path must report both, independently
    edges = {(0, 1): {"wr"}, (1, 0): {"wr"},          # G1c ring
             (1, 2): {"rw"}, (2, 1): {"rw"}}          # 2-rw ring
    res = kernels.analyze_edges(3, edges, max_dense=2)
    assert res["G1c"] and res["G2-item"]
    dense = kernels.analyze_edges(3, edges, max_dense=4096)
    assert dense["G1c"] and dense["G2-item"]


def test_two_g_single_cycles_sharing_a_node_are_not_g2():
    # cycle A: 0-rw->1-ww->0; cycle B: 0-ww->2-rw->3-ww->0. Every simple
    # cycle has exactly one anti-dependency; stitching them through the
    # shared node 0 is not a simple cycle, so G2-item must stay False
    # (regression: the distinct-rw-sources test alone reports G2)
    edges = {(0, 1): {"rw"}, (1, 0): {"ww"}, (0, 2): {"ww"},
             (2, 3): {"rw"}, (3, 0): {"ww"}}
    for max_dense in (2, 4096):
        res = kernels.analyze_edges(4, edges, max_dense=max_dense)
        assert res["G-single"], max_dense
        assert not res["G2-item"], max_dense


def test_analyze_edges_self_loops():
    r = kernels.analyze_edges(2, {(0, 0): {"ww"}})
    assert r["G0"] and r["G1c"] and 0 in r["cycle-nodes"]
    r2 = kernels.analyze_edges(2, {(1, 1): {"rw"}})
    assert r2["G-single"] and not r2["G0"]
    # dense adapter with a true diagonal
    ww = np.zeros((2, 2), bool)
    ww[1, 1] = True
    assert kernels.analyze_graph(ww, np.zeros_like(ww),
                                 np.zeros_like(ww))["G0"]


def test_analyze_edges_oversized_g_single_and_g2():
    # oversized classification distinguishes one-rw from >=2-rw cycles
    e1 = {(0, 1): {"rw"}, (1, 2): {"ww"}, (2, 0): {"wr"}}
    r1 = kernels.analyze_edges(3, e1, max_dense=2)
    assert r1["G-single"] and not r1["G1c"] and not r1["G2-item"]
    e2 = {(0, 1): {"rw"}, (1, 2): {"ww"}, (2, 3): {"rw"}, (3, 0): {"ww"}}
    r2 = kernels.analyze_edges(4, e2, max_dense=2)
    assert r2["G2-item"] and not r2["G-single"]


def test_append_phantom_value_does_not_hide_anti_dependency():
    # a corrupt store fabricates value 9 in x's chain [1, 9, 2]; the
    # reader of [1] must still anti-depend on the (real) writer of 2,
    # closing a G-single cycle through T2 -wr-> R on k2
    h = history(
        _ok(0, [["append", "x", 1]], 0)
        + _ok(1, [["append", "x", 2], ["append", "k2", 5]], 2)
        + _ok(2, [["r", "x", [1]], ["r", "k2", [5]]], 4)
        + _ok(3, [["r", "x", [1, 9, 2]]], 6))
    res = list_append.check(h)
    assert res["valid?"] is False
    assert "G-single" in res["anomaly-types"]


def test_wr_history_synth_valid():
    from jepsen_tpu.checker import synth
    h = synth.wr_history(3000)
    res = wr.check(h)
    assert res["valid?"] is True
    assert res["txn-count"] == 3000


def test_g_single_certificate_has_exactly_one_rw():
    h = history(
        _ok(0, [["append", "x", 1], ["append", "y", 1]], 0)
        + _ok(1, [["r", "x", [1]], ["r", "y", []]], 2))
    res = list_append.check(h)
    cert = res["anomalies"]["G-single"][0]["cycle"]
    assert cert is not None
    assert cert[0]["index"] == cert[-1]["index"]  # closed cycle
    assert len(cert) == 3  # reader -rw-> writer -wr-> reader
