"""CockroachDB suite tests: DB command generation against the recording
dummy remote, the Postgres wire client against an in-process protocol
fake, error classification, and complete hermetic suite runs."""

import pytest

from fake_pg import FakePGServer

from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import cockroach, suite
from jepsen_tpu.suites.pg_proto import Conn, PGError


@pytest.fixture
def fake():
    f = FakePGServer()
    yield f
    f.stop()


def conn_fn(fake):
    return lambda node: Conn("127.0.0.1", fake.port)


def test_suite_registry():
    assert suite("cockroach") is cockroach


def test_db_setup_commands():
    """Setup installs the tarball, starts with --insecure --join, and
    runs `cockroach init` once on the first node (`auto.clj:60-140`)."""
    log = []
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "cockroach-v2.1.6.linux-amd64"})
    test = {"nodes": ["n1", "n2"], "tarball": "file:///tmp/crdb.tgz"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            cockroach.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "start --insecure" in cmds
    assert "--join=n1:26257,n2:26257" in cmds
    assert "init --insecure" in cmds
    # second node must not init
    log.clear()
    with control.with_remote(remote):
        sess = control.session("n2")
        with control.with_session("n2", sess):
            cockroach.db().setup(test, "n2")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "init --insecure" not in cmds


def test_pg_client_roundtrip(fake):
    c = Conn("127.0.0.1", fake.port)
    c.query("create table if not exists t (id int primary key, val int)")
    assert c.query("upsert into t (id, val) values (1, 5)") == (1, None)
    rows, cols = c.query("select val from t where id = 1")
    assert rows == [["5"]] and cols == ["val"]
    c.query("begin")
    assert c.txn_status == "T"
    c.query("rollback")
    assert c.txn_status == "I"
    with pytest.raises(PGError):
        c.query("bogus")
    c.close()


def test_wr_txn_client(fake):
    t = {"sql-conn-fn": conn_fn(fake)}
    c = cockroach.WrTxnClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                     "value": [["w", 1, 9], ["r", 1, None]]})
    assert r["type"] == "ok"
    assert r["value"] == [["w", 1, 9], ["r", 1, 9]]


def test_serialization_conflict_is_definite_fail(fake):
    fake.fail_hook = lambda sql: ("40001", "restart transaction") \
        if "upsert" in sql.lower() else None
    t = {"sql-conn-fn": conn_fn(fake)}
    c = cockroach.WrTxnClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                     "value": [["w", 1, 9]]})
    assert r["type"] == "fail"
    assert r["error"][1] == "40001"
    # unknown SQLSTATE mid-write -> info
    fake.fail_hook = lambda sql: ("XX000", "boom") \
        if "upsert" in sql.lower() else None
    r2 = c.invoke(t, {"type": "invoke", "f": "txn", "process": 0,
                      "value": [["w", 1, 9]]})
    assert r2["type"] == "info"


def test_bank_client(fake):
    t = {"sql-conn-fn": conn_fn(fake), "accounts": [0, 1],
         "total-amount": 20}
    c = cockroach.BankClient().open(t, "n1")
    c.setup(t)
    r = c.invoke(t, {"type": "invoke", "f": "read", "process": 0})
    assert r["type"] == "ok" and sum(r["value"].values()) == 20
    x = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                     "value": {"from": 0, "to": 1, "amount": 5}})
    assert x["type"] == "ok"
    bad = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                       "value": {"from": 1, "to": 0, "amount": 50}})
    assert bad["type"] == "fail"


def test_g2_client_blocks_second_insert(fake):
    from jepsen_tpu.independent import ktuple
    t = {"sql-conn-fn": conn_fn(fake)}
    c = cockroach.G2Client().open(t, "n1")
    c.setup(t)
    r1 = c.invoke(t, {"type": "invoke", "f": "insert", "process": 0,
                      "value": ktuple(3, [7, None])})
    assert r1["type"] == "ok"
    r2 = c.invoke(t, {"type": "invoke", "f": "insert", "process": 1,
                      "value": ktuple(3, [None, 8])})
    assert r2["type"] == "fail"


def test_cockroach_test_map_builds():
    t = cockroach.cockroach_test(
        {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
         "ssh": {"dummy": True}, "workload": "bank", "time-limit": 5,
         "faults": ["none"]})
    assert t["name"] == "cockroach-bank"
    assert t["generator"] is not None


def test_clock_faults_use_native_tools():
    """The clock fault family maps to the framework clock package,
    which compiles/drives the native C++ time tools
    (`nemesis.clj:201-270` parity)."""
    t = cockroach.cockroach_test(
        {"nodes": ["n1"], "concurrency": 1, "ssh": {"dummy": True},
         "workload": "bank", "time-limit": 1, "faults": ["clock"]})
    from jepsen_tpu.nemesis.time import ClockNemesis

    def nemeses(nem):
        yield nem
        for attr in ("nemeses", "pairs"):
            for x in getattr(nem, attr, None) or []:
                yield from nemeses(x[1] if isinstance(x, tuple) else x)

    assert any(isinstance(x, ClockNemesis) for x in nemeses(t["nemesis"]))


@pytest.mark.parametrize("workload", sorted(cockroach.WORKLOADS))
def test_hermetic_suite_run(tmp_path, fake, workload):
    """End to end: dummy remote for the cluster, fake Postgres-protocol
    CockroachDB for the data plane, full checker stack. The fake is
    serializable, so every workload must verify."""
    opts = {
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 6,
        "ssh": {"dummy": True},
        "workload": workload,
        "rate": 500,
        # 2s (was 3): the menu grew to 8 workloads (monotonic /
        # sequential / comments), so each run gets a slightly tighter
        # budget to keep the file's wall time flat; at rate 500 a 2s
        # run still journals ~1k ops, plenty for every checker here
        "time-limit": 2,
        "ops-per-key": 20,
        "faults": ["none"],
        "store-dir": str(tmp_path / "store"),
    }
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = cockroach.cockroach_test(opts)
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["sql-conn-fn"] = conn_fn(fake)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    assert len(done["history"]) > 10
