"""FaunaDB suite tests: the query AST + wire client against the
in-process fake (real HTTP, versioned temporal store), error
classification, checker units, topology state machine, and hermetic
end-to-end runs for register, g2, monotonic, pages, bank, set,
internal, multimonotonic, and a topology-nemesis run."""

import pytest

from fake_fauna import FakeFauna

import jepsen_tpu.db as jdb
import jepsen_tpu.os_ as jos
from jepsen_tpu import core
from jepsen_tpu.suites import faunadb as fdb
from jepsen_tpu.suites import fauna_query as q
from jepsen_tpu.suites.faunadb import (FaunaConn, FaunaError, Incomparable,
                                       map_compare, pages_read_errs,
                                       with_errors)


@pytest.fixture
def fake():
    f = FakeFauna()
    yield f
    f.stop()


def conn_fn(fake):
    return lambda node, linearized=False: FaunaConn(
        "127.0.0.1", fake.port, linearized=linearized, timeout_s=5.0)


# -- wire client + AST -------------------------------------------------------

def test_query_roundtrip(fake):
    c = FaunaConn("127.0.0.1", fake.port)
    c.query(q.create_class({"name": "things"}))
    r = q.ref("things", 1)
    res = c.query(q.create(r, {"data": {"x": 41}}))
    assert res["data"] == {"x": 41}
    assert c.query(q.exists(r)) is True
    res = c.query(q.update(r, {"data": {"x": 42}}))
    assert res["data"]["x"] == 42
    assert c.query(q.select(["data", "x"], q.get(r))) == 42
    # let / arithmetic / comparison forms
    assert c.query(q.let({"a": 40}, q.add(q.var("a"), 2))) == 42
    assert c.query(q.lt(1, 2, 3)) is True
    assert c.query(q.if_(q.eq(1, 2), "y", "n")) == "n"
    c.close()


def test_temporal_at_reads_past_snapshot(fake):
    """FaunaDB is temporal: at-queries see the store as of a past ts."""
    c = FaunaConn("127.0.0.1", fake.port)
    c.query(q.create_class({"name": "reg"}))
    r = q.ref("reg", 0)
    c.query(q.create(r, {"data": {"v": 1}}))
    ts1 = c.query(q.NOW)
    c.query(q.update(r, {"data": {"v": 2}}))
    now_v = c.query(q.select(["data", "v"], q.get(r)))
    past_v = c.query(q.at(ts1, q.select(["data", "v"], q.get(r))))
    assert (now_v, past_v) == (2, 1)
    # and events lists the version history
    evs = c.query(q.paginate(q.events(r), size=10))["data"]
    assert [e["action"] for e in evs] == ["create", "update"]
    c.close()


def test_abort_rolls_back(fake):
    c = FaunaConn("127.0.0.1", fake.port)
    c.query(q.create_class({"name": "t"}))
    r = q.ref("t", 1)
    with pytest.raises(FaunaError) as ei:
        c.query(q.do(q.create(r, {"data": {"x": 1}}),
                     q.abort("nope")))
    assert "nope" in ei.value.description
    assert c.query(q.exists(r)) is False  # create was rolled back
    c.close()


def test_index_match_and_pagination(fake):
    c = FaunaConn("127.0.0.1", fake.port)
    c.query(q.create_class({"name": "el"}))
    c.query(q.create_index({"name": "all", "source": q.class_("el"),
                            "active": True,
                            "values": [{"field": ["data", "v"]}]}))
    for v in range(10):
        c.query(q.create(q.ref("el", v), {"data": {"v": v}}))
    rows = fdb.query_all(c, q.match(q.index("all")), size=3)
    assert rows == list(range(10))
    c.close()


def test_error_classification(fake):
    """with-errors taxonomy (`client.clj:375-418`)."""
    op = {"f": "read", "process": 0}
    wop = {"f": "write", "process": 0}
    fake.fail_hook = lambda e: (503, "unavailable", "replica down")
    c = FaunaConn("127.0.0.1", fake.port)
    r = with_errors(op, frozenset({"read"}),
                    lambda: c.query(q.NOW), pause_s=0)
    assert r["type"] == "fail" and r["error"][0] == "unavailable"
    r = with_errors(wop, frozenset({"read"}),
                    lambda: c.query(q.NOW), pause_s=0)
    assert r["type"] == "info"
    fake.fail_hook = lambda e: (500, "internal server error",
                                "fauna.repo.UninitializedException: x")
    r = with_errors(wop, frozenset(),
                    lambda: c.query(q.NOW), pause_s=0)
    assert r == {**wop, "type": "fail", "error": "repo-uninitialized"}
    fake.fail_hook = lambda e: (500, "internal server error",
                                "Transaction Coordinator is shut down")
    r = with_errors(wop, frozenset(),
                    lambda: c.query(q.NOW), pause_s=0)
    assert r["error"] == "transaction-coordinator-shut-down"
    fake.fail_hook = None
    c.close()


def test_connection_refused_classified_as_fail():
    op = {"f": "write", "process": 0}

    def boom():
        c = FaunaConn("127.0.0.1", 1, timeout_s=0.2)  # nothing listens
        return c.query(q.NOW)
    r = with_errors(op, frozenset(), boom, pause_s=0)
    assert r["type"] == "fail"
    assert r["error"] in ("connection-refused",) or \
        r["error"][0] == "connect"


# -- checker units -----------------------------------------------------------

def test_pages_read_errs():
    idx = {1: frozenset({1, 2}), 2: frozenset({1, 2}),
           3: frozenset({3, 4}), 4: frozenset({3, 4})}
    assert pages_read_errs(idx, {1, 2, 3, 4}) == []
    errs = pages_read_errs(idx, {1, 3, 4})
    assert errs and errs[0]["expected"] == [1, 2]
    assert pages_read_errs(idx, set()) == []


def test_map_compare():
    assert map_compare({"x": 1}, {"x": 2}) == -1
    assert map_compare({"x": 2, "y": 5}, {"x": 1}) == 1
    assert map_compare({"x": 1}, {"y": 9}) == 0
    with pytest.raises(Incomparable):
        map_compare({"x": 1, "y": 2}, {"x": 2, "y": 1})


def test_read_skew_checker_detects_cycle():
    hist = [
        {"type": "ok", "f": "read", "process": 0,
         "value": {"ts": "1", "registers": {
             "x": {"value": 1}, "y": {"value": 2}}}},
        {"type": "ok", "f": "read", "process": 1,
         "value": {"ts": "2", "registers": {
             "x": {"value": 2}, "y": {"value": 1}}}},
    ]
    res = fdb.ReadSkewChecker().check({}, hist, {})
    assert res["valid?"] is False and res["cycles"]
    ok = [
        {"type": "ok", "f": "read", "process": 0,
         "value": {"ts": "1", "registers": {
             "x": {"value": 1}, "y": {"value": 1}}}},
        {"type": "ok", "f": "read", "process": 1,
         "value": {"ts": "2", "registers": {
             "x": {"value": 2}, "y": {"value": 2}}}},
    ]
    assert fdb.ReadSkewChecker().check({}, ok, {})["valid?"] is True


def test_ts_order_checker():
    hist = [
        {"type": "ok", "f": "read", "index": 0,
         "value": {"ts": "1", "registers": {"x": {"value": 5}}}},
        {"type": "ok", "f": "read", "index": 1,
         "value": {"ts": "2", "registers": {"x": {"value": 3}}}},
    ]
    res = fdb.TsOrderChecker().check({}, hist, {})
    assert res["valid?"] is False
    assert res["errors"][0]["errors"]["x"][0]["value"] == 5


def test_monotonic_checker():
    hist = [
        {"type": "ok", "f": "read", "process": 3, "value": ["1", 4]},
        {"type": "ok", "f": "read", "process": 3, "value": ["2", 3]},
    ]
    res = fdb.MonotonicChecker().check({}, hist, {})
    assert res["valid?"] is False and res["value-errors"]


def test_internal_op_errors():
    ok_op = {"type": "ok", "f": "create-tabby-arr",
             "value": {"tabbies-0": [], "tabby": {"data": {"name": 7}},
                       "tabbies-1": [7]}}
    assert fdb.internal_op_errors(ok_op) == []
    bad = {"type": "ok", "f": "create-tabby-arr",
           "value": {"tabbies-0": [7], "tabby": {"data": {"name": 7}},
                     "tabbies-1": []}}
    errs = fdb.internal_op_errors(bad)
    assert {e["type"] for e in errs} == {"present-before-create",
                                        "missing-after-create"}


# -- topology ---------------------------------------------------------------

def test_topology_state_machine():
    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"], "replicas": 2}
    topo = fdb.initial_topology(test)
    assert topo["replica-count"] == 2
    by_rep = fdb.nodes_by_replica(topo)
    assert sorted(by_rep) == ["replica-0", "replica-1"]
    # full cluster: only removes possible
    assert fdb.add_ops(test, topo) == []
    removes = fdb.remove_ops(test, topo)
    assert {o["f"] for o in removes} == {"remove-node"}
    # apply a removal, then adding it back becomes possible
    op = removes[0]
    topo2 = fdb.apply_topo_op(topo, op)
    assert fdb.get_node(topo2, op["value"])["state"] == "removing"
    topo3 = {**topo2, "nodes": [n for n in topo2["nodes"]
                                if n["node"] != op["value"]]}
    adds = fdb.add_ops(test, topo3)
    assert [o["value"]["node"] for o in adds] == [op["value"]]
    topo4 = fdb.apply_topo_op(topo3, adds[0])
    assert fdb.get_node(topo4, op["value"])["state"] == "active"


def test_all_combos_and_workload_options():
    combos = fdb.all_combos({"a": [1, 2], "b": [True, False]})
    assert len(combos) == 4
    allw = fdb.all_workload_options(fdb.WORKLOAD_OPTIONS)
    assert {"workload": "register"} in allw
    assert len(allw) > 20


# -- hermetic end-to-end runs ------------------------------------------------

def _run(fake, tmp_path, workload, time_limit=3, nemesis=(), **opts):
    t = fdb.faunadb_test({
        "nodes": ["n1", "n2", "n3"], "concurrency": 6,
        "ssh": {"dummy": True}, "workload": workload,
        "rate": 200, "time-limit": time_limit,
        "nemesis": list(nemesis),
        "store-dir": str(tmp_path),
        "fauna-conn-fn": conn_fn(fake),
        "fauna-conn-retry-delay": 0.0,
        **opts})
    t["db"] = jdb.noop
    t["os"] = jos.noop
    return core.run(t)


def test_e2e_register(fake, tmp_path):
    done = _run(fake, tmp_path, "register",
                **{"ops-per-key": 30, "register-stagger": 0.005,
                   "register-delay": 0.0})
    assert done["results"]["valid?"] is True
    assert len(done["history"]) > 20
    # linearizable sub-result present per key
    wl = done["results"]["workload"]
    assert wl["valid?"] is True


def test_e2e_g2(fake, tmp_path):
    done = _run(fake, tmp_path, "g2")
    assert done["results"]["valid?"] is True
    wl = done["results"]["workload"]
    assert wl["key-count"] > 0


def test_e2e_monotonic(fake, tmp_path):
    """Exercises the at-query-jitter path: read-at ops query a
    jittered past timestamp (the fake's counter timestamps get a
    counter-space jitter fn)."""
    import random as _random

    def jitter(ts, jitter_ms):
        n = int(ts.rstrip("Z"))
        return f"{max(1, n - _random.randrange(3)):019d}Z"

    done = _run(fake, tmp_path, "monotonic",
                **{"at-query-jitter": 10_000,
                   "fauna-jitter-time-fn": jitter})
    assert done["results"]["valid?"] is True
    incs = [o for o in done["history"]
            if o.get("f") == "inc" and o.get("type") == "ok"]
    assert incs, "monotonic run must land increments"
    read_ats = [o for o in done["history"]
                if o.get("f") == "read-at" and o.get("type") == "ok"]
    assert read_ats, "read-at ops must land"


def test_e2e_pages(fake, tmp_path):
    done = _run(fake, tmp_path, "pages",
                **{"pages-elements": 40, "ops-per-key": 30})
    assert done["results"]["valid?"] is True
    assert done["results"]["workload"]["valid?"] is True


def test_e2e_bank(fake, tmp_path):
    done = _run(fake, tmp_path, "bank", **{"bank-delay": 0.005})
    assert done["results"]["valid?"] is True
    reads = [o for o in done["history"]
             if o.get("f") == "read" and o.get("type") == "ok"]
    assert reads and all(sum(r["value"].values()) == 100 for r in reads)


def test_e2e_bank_index(fake, tmp_path):
    done = _run(fake, tmp_path, "bank-index",
                **{"serialized-indices": True, "bank-delay": 0.005})
    assert done["results"]["valid?"] is True


def test_e2e_set_strong_read(fake, tmp_path):
    done = _run(fake, tmp_path, "set",
                **{"strong-read": True, "serialized-indices": True})
    assert done["results"]["valid?"] is True


def test_e2e_internal(fake, tmp_path):
    done = _run(fake, tmp_path, "internal",
                **{"serialized-indices": True})
    assert done["results"]["valid?"] is True


def test_e2e_multimonotonic(fake, tmp_path):
    done = _run(fake, tmp_path, "multimonotonic")
    assert done["results"]["valid?"] is True
    reads = [o for o in done["history"]
             if o.get("f") == "read" and o.get("type") == "ok"]
    assert reads


def test_e2e_register_with_topology_nemesis(fake, tmp_path):
    """Topology churn over the dummy remote: transitions execute, the
    topology map stays consistent, and the workload still verifies."""
    done = _run(fake, tmp_path, "register", time_limit=4,
                nemesis=("topology",),
                **{"ops-per-key": 30, "nemesis-interval": 0.5,
                   "replicas": 1, "register-stagger": 0.005,
                   "register-delay": 0.0})
    assert done["results"]["valid?"] is True
    topo_ops = [o for o in done["history"]
                if o.get("f") in ("add-node", "remove-node")]
    assert topo_ops, "topology nemesis must act"
    topo = done["topology"]["value"]
    names = [n["node"] for n in topo["nodes"]]
    assert len(names) == len(set(names))


def test_e2e_register_with_partition_nemesis(fake, tmp_path):
    # nemesis-interval 0.2, not 0.5: the nemesis generator is a fair
    # mix(start, stop), so "no start-partition in the whole run" has
    # probability (1/2)^picks — at 0.5 that's ~2^-8 per run, a real
    # flake observed in CI; at 0.2 (~20 picks in the 4 s window) it is
    # ~1e-6. Seeding doesn't help: nemesis draws interleave with
    # timing-dependent per-op process draws from the same rng.
    done = _run(fake, tmp_path, "register", time_limit=4,
                nemesis=("single-node-partition",),
                **{"ops-per-key": 30, "nemesis-interval": 0.2,
                   "register-stagger": 0.005, "register-delay": 0.0})
    assert done["results"]["valid?"] is True
    parts = [o for o in done["history"]
             if o.get("f") == "start-partition"]
    assert parts, "partition nemesis must act"


def test_workload_menu_registered():
    from jepsen_tpu.suites import suite
    mod = suite("faunadb")
    assert set(mod.WORKLOADS) == {
        "register", "bank", "bank-index", "g2", "set", "pages",
        "monotonic", "multimonotonic", "internal"}


def test_all_tests_sweep_builds():
    """The test-all sweep must build every workload x nemesis combo
    without constructing errors (matching runner.clj's all-tests)."""
    tests = list(fdb._all_tests({
        "nodes": ["n1", "n2", "n3"], "concurrency": 6,
        "ssh": {"dummy": True}, "time-limit": 1}))
    assert len(tests) == len(fdb.ALL_NEMESES) * len(
        fdb.all_workload_options(fdb.WORKLOAD_OPTIONS_EXPECTED_TO_PASS))
    names = {t["name"] for t in tests}
    assert any("register" in n for n in names)
    assert any("strong-read" in n for n in names)


def test_union_intersection_singleton(fake):
    """Set algebra forms (`query.clj:275-291,328-330`)."""
    c = FaunaConn("127.0.0.1", fake.port)
    c.query(q.create_class({"name": "s"}))
    for name, vals in (("by-a", [1, 2, 3]), ("by-b", [2, 3, 4])):
        c.query(q.create_index({
            "name": name, "source": q.class_("s"), "active": True,
            "terms": [{"field": ["data", "tag"]}],
            "values": [{"field": ["data", "v"]}]}))
    tag = {"by-a": "a", "by-b": "b"}
    for t, vs in (("a", [1, 2, 3]), ("b", [2, 3, 4])):
        for v in vs:
            c.query(q.create(q.class_("s"),
                             {"data": {"tag": t, "v": v}}))
    u = fdb.query_all(c, q.union(q.match(q.index("by-a"), "a"),
                                 q.match(q.index("by-b"), "b")))
    assert sorted(u) == [1, 2, 3, 4]
    i = fdb.query_all(c, q.intersection(q.match(q.index("by-a"), "a"),
                                        q.match(q.index("by-b"), "b")))
    assert sorted(i) == [2, 3]
    # singleton: one element for a live doc, empty for a missing one
    c.query(q.create(q.ref("s", 99), {"data": {"tag": "z", "v": 9}}))
    s = c.query(q.paginate(q.singleton(q.ref("s", 99)), size=4))
    assert len(s["data"]) == 1
    s = c.query(q.paginate(q.singleton(q.ref("s", 12345)), size=4))
    assert s["data"] == []
    c.close()


def test_timestamp_value_plotter_writes_svg(tmp_path):
    """read-at histories with timestamps produce the SVG plot."""
    hist = [{"type": "ok", "f": "read-at", "process": p,
             "value": [f"{10 + i:019d}", i]}
            for i, p in enumerate([0, 1, 0, 1, 0])]
    test = {"name": "tvplot", "start-time": "t0",
            "store-dir": str(tmp_path)}
    res = fdb.TimestampValuePlotter().check(test, hist, {})
    assert res["valid?"] is True
    svgs = list((tmp_path / "tvplot" / "t0").glob("timestamp-value-*.svg"))
    assert svgs, "plot must be written"
    assert "register value" in svgs[0].read_text()
