"""ZooKeeper suite tests: zoo.cfg generation, DB commands over the
dummy remote, the jute wire client against an in-process fake ZK over
real TCP, and a complete hermetic suite run."""

import pytest

from fake_zk import FakeZk

from jepsen_tpu import control, core
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import zk_proto, zookeeper


@pytest.fixture
def fake():
    f = FakeZk()
    f.port = f.start()
    yield f
    f.stop()


def test_node_ids():
    t = {"nodes": ["a", "b", "c"]}
    assert zookeeper.zk_node_ids(t) == {"a": 0, "b": 1, "c": 2}
    assert zookeeper.zoo_cfg_servers(t) == \
        "server.0=a:2888:3888\nserver.1=b:2888:3888\nserver.2=c:2888:3888"


def test_db_setup_commands():
    log = []
    remote = dummy.remote(log=log)
    test = {"nodes": ["n1", "n2"]}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            zookeeper.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "/etc/zookeeper/conf/myid" in cmds
    assert "/etc/zookeeper/conf/zoo.cfg" in cmds
    assert "service zookeeper restart" in cmds
    assert "server.0=n1:2888:3888" in cmds


def test_wire_client_roundtrip(fake):
    c = zk_proto.ZooKeeper("127.0.0.1", fake.port, timeout=2)
    assert c.session_id > 0
    assert c.exists("/jepsen") is None
    c.create("/jepsen", b"0")
    data, stat = c.get_data("/jepsen")
    assert data == b"0" and stat.version == 0
    c.set_data("/jepsen", b"3", 0)
    data, stat = c.get_data("/jepsen")
    assert data == b"3" and stat.version == 1
    # stale-version CAS fails with BADVERSION
    with pytest.raises(zk_proto.ZkError) as e:
        c.set_data("/jepsen", b"9", 0)
    assert e.value.code == zk_proto.BADVERSION
    c.close()


def test_client_register_semantics(fake):
    t = {"zk-port": fake.port, "zk-host-fn": lambda n: "127.0.0.1"}
    c = zookeeper.ZkClient().open(t, "n1")
    c.setup(t)
    assert c.invoke(t, {"f": "read", "process": 0})["value"] == 0
    assert c.invoke(t, {"f": "write", "value": 4,
                        "process": 0})["type"] == "ok"
    assert c.invoke(t, {"f": "cas", "value": [4, 2],
                        "process": 0})["type"] == "ok"
    assert c.invoke(t, {"f": "cas", "value": [4, 1],
                        "process": 0})["type"] == "fail"
    assert c.invoke(t, {"f": "read", "process": 0})["value"] == 2
    c.close(t)


def test_client_connection_errors():
    t = {"zk-port": 1, "zk-host-fn": lambda n: "127.0.0.1"}
    with pytest.raises(OSError):
        zookeeper.ZkClient(timeout_s=0.2).open(t, "n1")


def test_zk_test_map():
    t = zookeeper.zk_test({"nodes": ["n1"], "concurrency": 2,
                           "ssh": {"dummy": True}})
    assert t["name"] == "zookeeper"
    assert t["generator"] is not None


def test_hermetic_suite_run(tmp_path, fake):
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = zookeeper.zk_test({
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 3,
        "ssh": {"dummy": True},
        "time-limit": 3,
        "store-dir": str(tmp_path / "store"),
    })
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["nemesis"] = __import__("jepsen_tpu").nemesis.noop
    t["zk-port"] = fake.port
    t["zk-host-fn"] = lambda n: "127.0.0.1"
    # speed the clock up: 3s wall with 1s stagger is plenty
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, {k: v.get("valid?")
                                   for k, v in res.items()
                                   if isinstance(v, dict)}
    assert len(done["history"]) > 2
