"""End-to-end orchestrator tests, mirroring the reference's
`jepsen/test/jepsen/core_test.clj`: a complete run (OS → DB → generator →
history → checker) executes hermetically in-process against the dummy
remote and the atom DB/client."""

import random
import threading

import pytest

from jepsen_tpu import client as jclient
from jepsen_tpu import core, db as jdb, nemesis as jnemesis
from jepsen_tpu import generator as gen
from jepsen_tpu import os_ as jos
from jepsen_tpu import store, testkit
from jepsen_tpu.history import is_invoke, is_ok


def noop_test(tmp_path, **kw):
    t = testkit.noop_test()
    t["ssh"] = {"dummy": True}
    t["store-dir"] = str(tmp_path / "store")
    t.update(kw)
    return t


class TrackingClient(jclient.Client):
    """Tracks open connections in a shared set (core_test.clj:22-41)."""

    _uid = [0]
    _lock = threading.Lock()

    def __init__(self, conns, uid=None):
        self.conns = conns
        self.uid = uid

    def open(self, test, node):
        with self._lock:
            self._uid[0] += 1
            uid = self._uid[0]
        self.conns.add(uid)
        return TrackingClient(self.conns, uid)

    def invoke(self, test, op):
        return {**op, "type": "ok"}

    def close(self, test):
        self.conns.discard(self.uid)


def test_most_interesting_exception(tmp_path):
    """DB setup crashes on one node; sibling nodes die with barrier
    noise. The *interesting* exception must surface
    (core_test.clj:43-60)."""

    class DB(jdb.DB):
        def setup(self, test, node):
            if node == test["nodes"][2]:
                raise RuntimeError("hi")
            raise threading.BrokenBarrierError("oops")

    t = noop_test(tmp_path, name="interesting exception", db=DB())
    with pytest.raises(RuntimeError, match="^hi$"):
        core.run(t)


def test_basic_cas(tmp_path):
    """1000 mixed read/write/cas ops at concurrency 10 against the atom
    register; checks history shape and client/DB lifecycle bookkeeping
    (core_test.clj:62-120)."""
    state = testkit.AtomState()
    n = 1000
    rng = random.Random(45100)
    t = noop_test(
        tmp_path,
        name="basic cas",
        db=testkit.atom_db(state),
        client=testkit.atom_client(state, latency_s=0.0),
        concurrency=10,
        # The reference's version of this test leaves the first read as
        # a bare map (core_test.clj:76), which fill-in-op can hand to
        # the NEMESIS thread (a uniformly random free process) — then
        # nothing orders the first *client* read before the writers and
        # the "first read sees 0" assertion races (observed ~1/40 under
        # CPU load; the reference only runs its copy under the rarely
        # used :integration tag). Pinning the read to a client keeps
        # the assertion deterministic without changing what it proves.
        generator=gen.phases(
            gen.clients({"f": "read"}),
            gen.clients(gen.limit(n, gen.reserve(
                5, gen.repeat({"f": "read"}),
                gen.mix([
                    lambda: {"f": "write", "value": rng.randint(0, 4)},
                    lambda: {"f": "cas",
                             "value": [rng.randint(0, 4),
                                       rng.randint(0, 4)]},
                ]))))),
    )
    t = core.run(t)
    h = t["history"]

    # db teardown ran last
    assert state.read() == "done"

    # client lifecycle: n_nodes opens+setups first, then worker
    # open/close churn, then n_nodes teardowns+closes
    nn = len(t["nodes"])
    log = state.meta_log
    assert sorted(log[:2 * nn]) == ["open"] * nn + ["setup"] * nn
    assert sorted(log[-2 * nn:]) == ["close"] * nn + ["teardown"] * nn
    mid = log[2 * nn:-2 * nn]
    assert mid.count("open") == mid.count("close")

    assert t["results"]["valid?"] is True

    oks = [o for o in h if is_ok(o)]
    reads = [o for o in oks if o["f"] == "read"]
    assert reads[0]["value"] == 0  # first read sees the fresh DB

    assert len(h) == 2 * (n + 1)
    assert {o["f"] for o in h} == {"read", "write", "cas"}
    for o in h:
        if is_invoke(o) and o["f"] == "read":
            assert o.get("value") is None
        elif o["f"] == "read" and is_ok(o):
            assert 0 <= o["value"] <= 4
        elif o["f"] == "write":
            assert 0 <= o["value"] <= 4
        elif o["f"] == "cas":
            old, new = o["value"]
            assert 0 <= old <= 4 and 0 <= new <= 4

    # two-phase persistence landed
    assert store.load_history(t) is not None
    assert store.load_results(t)["valid?"] is True


def test_dummy_remote_lifecycle(tmp_path):
    """OS/DB setup+teardown and primary setup run over the (dummy)
    control layer, once per node, with sessions bound
    (core_test.clj:122-177, sans real SSH)."""
    os_startups, os_teardowns = {}, {}
    db_startups, db_teardowns = {}, {}
    db_primaries = []

    class OS(jos.OS):
        def setup(self, test, node):
            os_startups[node] = True

        def teardown(self, test, node):
            os_teardowns[node] = True

    class DB(jdb.DB, jdb.Primary):
        def setup(self, test, node):
            db_startups[node] = True

        def teardown(self, test, node):
            db_teardowns[node] = True

        def primaries(self, test):
            return test["nodes"][:1]

        def setup_primary(self, test, node):
            db_primaries.append(node)

    t = noop_test(tmp_path, name="dummy lifecycle", os=OS(), db=DB())
    t = core.run(t)
    assert t["results"]["valid?"] is True
    nodes = set(t["nodes"])
    assert set(os_startups) == set(os_teardowns) == nodes
    assert set(db_startups) == set(db_teardowns) == nodes
    assert db_primaries == [t["nodes"][0]]


def test_worker_recovery(tmp_path):
    """A client that always crashes consumes exactly n ops — crashed
    processes are retired and replaced, not re-fed the same op forever
    (core_test.clj:179-198)."""
    invocations = [0]
    n = 12

    class Crasher(jclient.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            invocations[0] += 1
            return 1 // 0

    core.run(noop_test(
        tmp_path,
        name="worker recovery",
        client=Crasher(),
        generator=gen.nemesis(None, gen.limit(n, gen.repeat({"f": "read"}))),
    ))
    assert invocations[0] == n


def test_generator_recovery(tmp_path):
    """A generator crash propagates without deadlocking workers parked
    at a phase barrier, and all clients get closed
    (core_test.clj:200-222)."""
    conns = set()

    def poison(test, ctx):
        if list(ctx.free_threads) == [0]:
            return 1 // 0
        return {"type": "invoke", "f": "meow"}

    t = noop_test(
        tmp_path,
        name="generator recovery",
        client=TrackingClient(conns),
        generator=gen.clients(gen.phases(
            gen.each_thread(gen.once(poison)),
            gen.once({"type": "invoke", "f": "done"}))),
    )
    with pytest.raises(gen.GenException) as ei:
        core.run(t)
    assert isinstance(ei.value.__cause__, ZeroDivisionError)
    assert conns == set()


@pytest.mark.parametrize("stage", ["open", "setup", "teardown", "close"])
def test_client_error_rethrown(tmp_path, stage):
    """Errors in client lifecycle hooks are rethrown from the run
    (core_test.clj:224-249)."""

    class C(jclient.Client):
        def open(self, test, node):
            assert stage != "open"
            return self

        def setup(self, test):
            assert stage != "setup"

        def invoke(self, test, op):
            return {**op, "type": "ok"}

        def teardown(self, test):
            assert stage != "teardown"

        def close(self, test):
            assert stage != "close"

    with pytest.raises(AssertionError):
        core.run(noop_test(tmp_path, client=C()))


@pytest.mark.parametrize("stage", ["setup", "teardown"])
def test_nemesis_error_rethrown(tmp_path, stage):
    class N(jnemesis.Nemesis):
        def setup(self, test):
            assert stage != "setup"
            return self

        def invoke(self, test, op):
            return op

        def teardown(self, test):
            assert stage != "teardown"

    with pytest.raises(AssertionError):
        core.run(noop_test(tmp_path, nemesis=N()))


def test_synchronize_barrier(tmp_path):
    """DB setup threads can rendezvous via core.synchronize
    (core.clj:44-57)."""
    order = []

    class DB(jdb.DB):
        def setup(self, test, node):
            order.append(("pre", node))
            core.synchronize(test)
            order.append(("post", node))

    t = noop_test(tmp_path, db=DB())
    core.run(t)
    pres = [i for i, (ph, _) in enumerate(order) if ph == "pre"]
    posts = [i for i, (ph, _) in enumerate(order) if ph == "post"]
    assert max(pres) < min(posts)


def test_prepare_test_defaults():
    t = core.prepare_test({"nodes": ["a", "b"]})
    assert t["concurrency"] == 2
    assert isinstance(t["barrier"], threading.Barrier)
    assert t["start-time"]
    t0 = core.prepare_test({"nodes": []})
    assert t0["barrier"] == core.NO_BARRIER


def test_prepare_test_rejects_duplicate_nodes():
    """The doc/plan.md 'Validation' graduation: a duplicated node used
    to surface much later as a bind error on the node — it must fail
    at test construction with a message naming the culprits."""
    import pytest

    with pytest.raises(ValueError, match="n2"):
        core.prepare_test({"nodes": ["n1", "n2", "n2", "n3"]})
    with pytest.raises(ValueError, match="more than once"):
        core.prepare_test({"nodes": ["n1"] * 3})
    # distinct nodes still pass untouched
    assert core.prepare_test({"nodes": ["n1", "n2"]})["nodes"] == \
        ["n1", "n2"]
