"""YugaByte suite tests: dual-API workload menu, master/tserver DB
automation against the recording dummy remote, the CQL wire client
against an in-process protocol fake, error classification, the
master/tserver process nemesis, and complete hermetic suite runs over
both the YCQL (fake CQL server) and YSQL (fake Postgres server) data
planes."""

import pytest

from fake_cql import FakeCQLServer
from fake_pg import FakePGServer

from jepsen_tpu import control, core, models
from jepsen_tpu.control import dummy
from jepsen_tpu.suites import suite, yugabyte
from jepsen_tpu.suites.cql_proto import CQLError, Conn
from jepsen_tpu.suites.cql_proto import ERR_WRITE_TIMEOUT, ERR_UNAVAILABLE


@pytest.fixture
def fake():
    f = FakeCQLServer()
    yield f
    f.stop()


@pytest.fixture
def fake_pg():
    f = FakePGServer()
    yield f
    f.stop()


def cql_conn_fn(fake):
    return lambda node: Conn("127.0.0.1", fake.port)


def pg_conn_fn(fake_pg):
    from jepsen_tpu.suites.pg_proto import Conn as PGConn
    return lambda node: PGConn("127.0.0.1", fake_pg.port)


def test_suite_registry():
    assert suite("yugabyte") is yugabyte


def test_master_nodes():
    t = {"nodes": ["n1", "n2", "n3", "n4", "n5"],
         "replication-factor": 3}
    assert yugabyte.master_nodes(t) == ["n1", "n2", "n3"]
    assert yugabyte.master_addresses(t) == "n1:7100,n2:7100,n3:7100"
    assert yugabyte.master_node(t, "n2")
    assert not yugabyte.master_node(t, "n5")


def test_db_setup_commands():
    """Masters start on the first RF nodes with --master_addresses and
    --replication_factor; tservers everywhere with
    --tserver_master_addrs; ysql adds the pgsql proxy flags
    (`auto.clj:334-413`)."""
    log = []
    remote = dummy.remote(
        log=log, responses={r"ls -A \.": "yugabyte-1.3.1.0"})
    test = {"nodes": ["n1", "n2", "n3", "n4"], "replication-factor": 3,
            "tarball": "file:///tmp/yb.tgz", "api": "ysql"}
    with control.with_remote(remote):
        sess = control.session("n1")
        with control.with_session("n1", sess):
            yugabyte.db().setup(test, "n1")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "post_install.sh" in cmds
    assert "yb-master" in cmds
    assert "--master_addresses n1:7100,n2:7100,n3:7100" in cmds
    assert "--replication_factor 3" in cmds
    assert "--tserver_master_addrs n1:7100,n2:7100,n3:7100" in cmds
    assert "--start_pgsql_proxy" in cmds
    assert "limits.d/jepsen.conf" in cmds
    # n4 is not a master: no yb-master daemon start
    log.clear()
    with control.with_remote(remote):
        sess = control.session("n4")
        with control.with_session("n4", sess):
            yugabyte.db().setup(test, "n4")
    cmds = " ; ".join(a.get("cmd", "") for _h, _c, a in log)
    assert "yb-master" not in cmds.replace("yb-master.pid", "")
    assert "yb-tserver" in cmds


def test_cql_client_roundtrip(fake):
    c = Conn("127.0.0.1", fake.port)
    c.query("CREATE TABLE IF NOT EXISTS jepsen.t "
            "(id INT PRIMARY KEY, val INT)")
    c.query("INSERT INTO jepsen.t (id, val) VALUES (1, 5)")
    rows, cols = c.query("SELECT val FROM jepsen.t WHERE id = 1")
    assert rows == [[5]] and cols == ["val"]
    # CQL insert is an upsert
    c.query("INSERT INTO jepsen.t (id, val) VALUES (1, 7)")
    rows, _ = c.query("SELECT val FROM jepsen.t WHERE id = 1")
    assert rows == [[7]]
    # conditional update: applied + not-applied
    rows, cols = c.query("UPDATE jepsen.t SET val = 9 WHERE id = 1 "
                         "IF val = 7")
    assert rows[0][cols.index("[applied]")] is True
    rows, cols = c.query("UPDATE jepsen.t SET val = 9 WHERE id = 1 "
                         "IF val = 3")
    assert rows[0][cols.index("[applied]")] is False
    # counters
    c.query("CREATE TABLE jepsen.counter (id INT PRIMARY KEY, "
            "count COUNTER)")
    c.query("UPDATE jepsen.counter SET count = count + 5 WHERE id = 0")
    c.query("UPDATE jepsen.counter SET count = count - 2 WHERE id = 0")
    rows, _ = c.query("SELECT count FROM jepsen.counter WHERE id = 0")
    assert rows == [[3]]
    with pytest.raises(CQLError):
        c.query("bogus statement")
    c.close()


def test_cql_transaction_batch(fake):
    c = Conn("127.0.0.1", fake.port)
    c.query("CREATE TABLE jepsen.accounts (id INT PRIMARY KEY, "
            "balance BIGINT)")
    c.query("INSERT INTO jepsen.accounts (id, balance) VALUES (0, 10)")
    c.query("INSERT INTO jepsen.accounts (id, balance) VALUES (1, 0)")
    c.query("BEGIN TRANSACTION "
            "UPDATE jepsen.accounts SET balance = balance - 3 "
            "WHERE id = 0;"
            "UPDATE jepsen.accounts SET balance = balance + 3 "
            "WHERE id = 1;"
            "END TRANSACTION;")
    rows, _ = c.query("SELECT id, balance FROM jepsen.accounts")
    assert {r[0]: r[1] for r in rows} == {0: 7, 1: 3}
    c.close()


def test_cql_error_classification(fake):
    """Timeouts on writes are indeterminate; on reads they fail;
    unavailable always fails; definite-conflict messages fail
    (`ycql/client.clj:197-245`)."""
    t = {"cql-conn-fn": cql_conn_fn(fake), "accounts": [0, 1],
         "total-amount": 20}
    c = yugabyte.CQLBank().open(t, "n1")
    c.setup(t)

    fake.fail_hook = lambda cql: (ERR_WRITE_TIMEOUT, "write timed out") \
        if "BEGIN TRANSACTION" in cql else None
    r = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                     "value": {"from": 0, "to": 1, "amount": 5}})
    assert r["type"] == "info"

    fake.fail_hook = lambda cql: (ERR_WRITE_TIMEOUT, "timed out") \
        if "SELECT" in cql else None
    r = c.invoke(t, {"type": "invoke", "f": "read", "process": 0})
    assert r["type"] == "fail"

    fake.fail_hook = lambda cql: (ERR_UNAVAILABLE, "not enough replicas") \
        if "BEGIN TRANSACTION" in cql else None
    r = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                     "value": {"from": 0, "to": 1, "amount": 5}})
    assert r["type"] == "fail"

    fake.fail_hook = lambda cql: \
        (0x0000, "Conflicts with committed transaction x") \
        if "BEGIN TRANSACTION" in cql else None
    r = c.invoke(t, {"type": "invoke", "f": "transfer", "process": 0,
                     "value": {"from": 0, "to": 1, "amount": 5}})
    assert r["type"] == "fail"
    fake.fail_hook = None


def test_cql_single_key_cas(fake):
    from jepsen_tpu.independent import ktuple
    t = {"cql-conn-fn": cql_conn_fn(fake)}
    c = yugabyte.CQLSingleKey().open(t, "n1")
    c.setup(t)
    assert c.invoke(t, {"type": "invoke", "f": "write", "process": 0,
                        "value": (3, 1)})["type"] == "ok"
    assert c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                        "value": (3, (1, 4))})["type"] == "ok"
    assert c.invoke(t, {"type": "invoke", "f": "cas", "process": 0,
                        "value": (3, (1, 2))})["type"] == "fail"
    r = c.invoke(t, {"type": "invoke", "f": "read", "process": 0,
                     "value": (3, None)})
    assert r["type"] == "ok" and r["value"] == ktuple(3, 4)


def test_multi_register_model():
    m = models.multi_register()
    m2 = m.step({"value": [["w", 0, 1], ["w", 2, 3]]})
    assert not models.is_inconsistent(m2)
    ok = m2.step({"value": [["r", 0, 1], ["r", 2, 3]]})
    assert not models.is_inconsistent(ok)
    bad = m2.step({"value": [["r", 0, 2]]})
    assert models.is_inconsistent(bad)
    # nil reads are always legal
    assert not models.is_inconsistent(m.step({"value": [["r", 1, None]]}))


def test_process_nemesis_targets_masters():
    """kill-master only touches master nodes; start-tserver heals
    every node (`nemesis.clj:18-45`)."""
    log = []
    remote = dummy.remote(log=log)
    db_ = yugabyte.db()
    test = {"nodes": ["n1", "n2", "n3", "n4"], "replication-factor": 3,
            "db": db_, "api": "ycql"}
    with control.with_remote(remote):
        test["sessions"] = {n: control.session(n) for n in test["nodes"]}
        nem = yugabyte.ProcessNemesis()
        done = nem.invoke(test, {"type": "info", "f": "kill-master",
                                 "value": None})
        assert set(done["value"]) <= {"n1", "n2", "n3"}
        done = nem.invoke(test, {"type": "info", "f": "start-tserver",
                                 "value": None})
        assert set(done["value"]) == {"n1", "n2", "n3", "n4"}


def test_nemesis_package_menu():
    pkg = yugabyte.nemesis_package(
        {"faults": ["kill-tserver", "partition", "clock"]})
    fs = pkg["nemesis"].fs()
    assert "kill-tserver" in fs and "start-partition" in fs \
        and "bump" in fs
    assert pkg["generator"] is not None
    assert pkg["final-generator"]


def test_workload_menu_is_dual_api():
    names = set(yugabyte.WORKLOADS)
    assert {"ycql/bank", "ycql/counter", "ycql/set", "ycql/set-index",
            "ycql/long-fork", "ycql/single-key-acid",
            "ycql/multi-key-acid", "ysql/bank", "ysql/bank-multitable",
            "ysql/counter", "ysql/set", "ysql/long-fork",
            "ysql/single-key-acid", "ysql/multi-key-acid",
            "ysql/append", "ysql/default-value"} <= names


def _run_opts(tmp_path, workload):
    return {
        "nodes": ["n1", "n2", "n3"],
        "concurrency": 6,
        "ssh": {"dummy": True},
        "workload": workload,
        "rate": 500,
        "time-limit": 3,
        "faults": ["none"],
        # drop the reference's 1 s per-key stagger
        # (`single_key_acid.clj:40`) so 3 s yields a real history
        "acid-stagger": 0.01,
        "store-dir": str(tmp_path / "store"),
    }


YCQL_WORKLOADS = sorted(w for w in yugabyte.WORKLOADS
                        if w.startswith("ycql/"))
YSQL_WORKLOADS = sorted(w for w in yugabyte.WORKLOADS
                        if w.startswith("ysql/"))


@pytest.mark.parametrize("workload", YCQL_WORKLOADS)
def test_hermetic_ycql_run(tmp_path, fake, workload):
    """End to end over the fake CQL server: linearizable by
    construction, so every workload must verify."""
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = yugabyte.yugabyte_test(_run_opts(tmp_path, workload))
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["cql-conn-fn"] = cql_conn_fn(fake)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    assert len(done["history"]) > 10


@pytest.mark.parametrize("workload", YSQL_WORKLOADS)
def test_hermetic_ysql_run(tmp_path, fake_pg, workload):
    """End to end over the fake Postgres server."""
    import jepsen_tpu.db
    import jepsen_tpu.os_
    t = yugabyte.yugabyte_test(_run_opts(tmp_path, workload))
    t["db"] = jepsen_tpu.db.noop
    t["os"] = jepsen_tpu.os_.noop
    t["sql-conn-fn"] = pg_conn_fn(fake_pg)
    done = core.run(t)
    res = done["results"]
    assert res["valid?"] is True, res
    assert len(done["history"]) > 10
