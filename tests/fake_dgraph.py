"""In-process dgraph fake: an HTTP server implementing the alpha's
transactional HTTP API (/alter, /query, /mutate, /commit) over a
versioned triple store with snapshot-isolation semantics:

  * every transaction reads at its start-ts snapshot,
  * writes are buffered server-side per start-ts,
  * /commit detects write-write conflicts ((uid, pred) keys, plus
    (pred, value) index keys for @upsert predicates) against
    transactions committed after start-ts, answering with dgraph's
    "Transaction has been aborted. Please retry." message,

plus a zero /state + /moveTablet surface for the tablet-mover nemesis.
Queries parse exactly the graphql+- shapes the suite client emits:
``{ q(func: eq(pred, $var)) { fields } }`` and ``func: uid($u)``.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

ABORTED_MSG = "Transaction has been aborted. Please retry"

_QUERY_RE = re.compile(
    r"\{\s*(?P<block>\w+)\s*\(\s*func:\s*(?P<fn>eq|uid)\s*\(\s*"
    r"(?P<arg1>[\w\-\$]+)\s*(?:,\s*(?P<arg2>[^)]+))?\)\s*\)\s*"
    r"\{(?P<fields>[^}]*)\}\s*\}")


class FakeDgraph:
    def __init__(self, float_coerce: bool = False):
        # float_coerce models real dgraph's JSON number handling:
        # integers round-trip through float64, silently corrupting
        # values beyond 2^53 (what the types workload exists to catch)
        self.float_coerce = float_coerce
        self.schema: dict[str, dict] = {}   # pred -> {index, upsert, type}
        # uid -> list of (ts, {pred: value} | None)
        self.nodes: dict[str, list] = {}
        self.ts = 0
        self.next_uid = 0
        # start_ts -> {"writes": [(uid, {pred: val|None})], "ckeys": set}
        self.txns: dict[int, dict] = {}
        self.commit_log: list[tuple[int, frozenset]] = []  # (commit_ts, ckeys)
        self.lock = threading.Lock()
        self.fail_hook = None   # (path, body) -> None | message str
        self.moves: list = []

        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, doc, status=200):
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                u = urlparse(self.path)
                if u.path == "/state":
                    self._reply(fake.state())
                elif u.path == "/moveTablet":
                    qs = parse_qs(u.query)
                    fake.moves.append((qs.get("tablet", [""])[0],
                                      qs.get("group", [""])[0]))
                    self._reply({"data": "ok"})
                else:
                    self._reply({"errors": [{"message": "not found"}]},
                                404)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                u = urlparse(self.path)
                qs = parse_qs(u.query)
                start_ts = int(qs["startTs"][0]) if "startTs" in qs \
                    else None
                try:
                    body = json.loads(raw) if raw else {}
                    hook = fake.fail_hook
                    if hook is not None:
                        msg = hook(u.path, body)
                        if msg is not None:
                            raise Abort(msg)
                    if u.path == "/alter":
                        doc = fake.alter(body)
                    elif u.path == "/query":
                        doc = fake.query(start_ts, body)
                    elif u.path == "/mutate":
                        doc = fake.mutate(start_ts, body)
                    elif u.path == "/commit":
                        doc = fake.commit(start_ts, body,
                                          abort="abort" in qs)
                    else:
                        raise Abort(f"unknown path {u.path}")
                    self._reply(doc)
                except Abort as e:
                    self._reply({"errors": [{"message": str(e)}]}, 409)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # -- store ---------------------------------------------------------------

    def _snapshot(self, uid: str, at: int) -> dict | None:
        out = None
        for ts, data in self.nodes.get(uid, ()):
            if ts > at:
                break
            out = data
        return out

    def _live_uids(self, at: int):
        for uid in list(self.nodes):
            data = self._snapshot(uid, at)
            if data is not None:
                yield uid, data

    # -- API ----------------------------------------------------------------

    def alter(self, body: dict) -> dict:
        with self.lock:
            for line in (body.get("schema") or "").splitlines():
                line = line.strip().rstrip(".").strip()
                if not line:
                    continue
                m = re.match(r"([\w\-]+):\s*(\S+)(.*)", line)
                if not m:
                    raise Abort(f"bad schema line {line!r}")
                pred, typ, rest = m.groups()
                self.schema[pred] = {
                    "type": typ,
                    "index": "@index" in rest,
                    "upsert": "@upsert" in rest}
            return {"data": {"code": "Success"}}

    def _txn(self, start_ts: int | None):
        if start_ts is None or start_ts == 0:
            self.ts += 1
            start_ts = self.ts
        t = self.txns.setdefault(start_ts,
                                 {"writes": [], "ckeys": set()})
        return start_ts, t

    def _ext(self, start_ts: int) -> dict:
        return {"txn": {"start_ts": start_ts,
                        "keys": [], "preds": []}}

    def query(self, start_ts, body: dict) -> dict:
        with self.lock:
            start_ts, txn = self._txn(start_ts)
            q = body.get("query") or ""
            vars_ = {k.lstrip("$"): v
                     for k, v in (body.get("vars") or {}).items()}
            if re.search(r"schema\s*\{", q):
                return {"data": {"schema": self.schema},
                        "extensions": self._ext(start_ts)}
            m = _QUERY_RE.search(q)
            if not m:
                raise Abort(f"unparseable query {q!r}")
            fields = [f for f in m.group("fields").split() if f]
            rows = []
            # overlay this txn's own writes on the snapshot
            overlay: dict[str, dict] = {}
            for uid, delta in txn["writes"]:
                cur = overlay.get(uid)
                if cur is None:
                    cur = dict(self._snapshot(uid, start_ts) or {})
                if delta is None:
                    cur = {}
                else:
                    for p, v in delta.items():
                        if v is None:
                            cur.pop(p, None)
                        else:
                            cur[p] = v
                overlay[uid] = cur

            def visible():
                seen = set(overlay)
                for uid, data in overlay.items():
                    if data:
                        yield uid, data
                for uid, data in self._live_uids(start_ts):
                    if uid not in seen:
                        yield uid, data

            if m.group("fn") == "uid":
                var = m.group("arg1").lstrip("$")
                target = vars_.get(var, m.group("arg1"))
                data = None
                if target in overlay:
                    data = overlay[target] or None
                else:
                    data = self._snapshot(target, start_ts)
                if data is not None:
                    rows.append((target, data))
            else:
                pred = m.group("arg1")
                arg2 = (m.group("arg2") or "").strip()
                var = arg2.lstrip("$")
                raw = vars_.get(var, arg2.strip('"'))
                sch = self.schema.get(pred)
                if sch is None or not sch["index"]:
                    raise Abort(f"Attribute {pred} not indexed")
                want = str(raw)
                for uid, data in visible():
                    if pred in data and str(data[pred]) == want:
                        rows.append((uid, data))
            out = []
            for uid, data in sorted(rows):
                row = {}
                for f in fields:
                    if f == "uid":
                        row["uid"] = uid
                    elif f in data:
                        row[f] = data[f]
                out.append(row)
            block = m.group("block")
            return {"data": {block: out},
                    "extensions": self._ext(start_ts)}

    def mutate(self, start_ts, body: dict) -> dict:
        with self.lock:
            start_ts, txn = self._txn(start_ts)
            uids_out = {}
            for obj in body.get("set") or []:
                obj = dict(obj)
                if self.float_coerce:
                    obj = {p: (int(float(v)) if isinstance(v, int)
                               and not isinstance(v, bool) else v)
                           for p, v in obj.items()}
                uid = obj.pop("uid", None)
                if uid is None:
                    self.next_uid += 1
                    uid = f"0x{self.next_uid:x}"
                    uids_out[f"blank-{len(uids_out)}"] = uid
                txn["writes"].append((uid, obj))
                for p, v in obj.items():
                    txn["ckeys"].add((uid, p))
                    sch = self.schema.get(p)
                    if sch and sch["upsert"]:
                        txn["ckeys"].add((p, str(v)))
            for obj in body.get("delete") or []:
                obj = dict(obj)
                uid = obj.pop("uid", None)
                if uid is None:
                    raise Abort("delete requires uid")
                if obj:
                    delta = {p: None for p in obj}
                    txn["writes"].append((uid, delta))
                    for p in obj:
                        txn["ckeys"].add((uid, p))
                else:
                    txn["writes"].append((uid, None))
                    data = self._snapshot(uid, start_ts) or {}
                    for p in data:
                        txn["ckeys"].add((uid, p))
            return {"data": {"uids": uids_out},
                    "extensions": self._ext(start_ts)}

    def commit(self, start_ts, body: dict, abort: bool = False) -> dict:
        with self.lock:
            txn = self.txns.pop(start_ts, None)
            if abort or txn is None:
                return {"data": {"code": "Done"}}
            ckeys = frozenset(txn["ckeys"])
            for commit_ts, other in self.commit_log:
                if commit_ts > start_ts and ckeys & other:
                    raise Abort(ABORTED_MSG)
            self.ts += 1
            commit_ts = self.ts
            for uid, delta in txn["writes"]:
                vs = self.nodes.setdefault(uid, [])
                cur = dict(self._snapshot(uid, commit_ts) or {})
                if delta is None:
                    vs.append((commit_ts, None))
                    continue
                for p, v in delta.items():
                    if v is None:
                        cur.pop(p, None)
                    else:
                        sch = self.schema.get(p, {"type": "int"})
                        if sch.get("type") == "[int]":
                            prev = cur.get(p)
                            cur[p] = (prev if isinstance(prev, list)
                                      else ([prev] if prev is not None
                                            else [])) + [v]
                        else:
                            cur[p] = v
                vs.append((commit_ts, cur))
            if ckeys:
                self.commit_log.append((commit_ts, ckeys))
            return {"data": {"code": "Done"},
                    "extensions": {"txn": {"commit_ts": commit_ts}}}

    def state(self) -> dict:
        preds = sorted(self.schema)
        half = len(preds) // 2 or 1
        return {"groups": {
            "1": {"tablets": {p: {"predicate": p, "groupId": 1}
                              for p in preds[:half]}},
            "2": {"tablets": {p: {"predicate": p, "groupId": 2}
                              for p in preds[half:]}}}}


class Abort(Exception):
    pass
