"""Interpreter end-to-end tests: a complete run (generator -> workers ->
history -> checker) in one process against the in-process atom register,
mirroring the reference's `core_test.clj/basic-cas-test` (62-121) and
worker-recovery tests (179-223)."""

import random

import pytest

from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu import models
from jepsen_tpu import testkit
from jepsen_tpu.checker.linear import analysis_host
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history import History
from jepsen_tpu.util import relative_time


def cas_mix(r):
    def g():
        which = r.random()
        if which < 0.4:
            return {"f": "read"}
        if which < 0.7:
            return {"f": "write", "value": r.randrange(5)}
        return {"f": "cas", "value": [r.randrange(5), r.randrange(5)]}
    return g


def run_test(test):
    with relative_time():
        return interpreter.run(test)


def test_basic_cas_run_is_linearizable():
    r = random.Random(45100)
    state = testkit.AtomState(0)
    test = testkit.noop_test()
    test.update({
        "concurrency": 5,
        "client": testkit.atom_client(state, latency_s=0.0005),
        "generator": gen.clients(gen.limit(300, cas_mix(r))),
    })
    hist = run_test(test)
    invokes = [o for o in hist if o["type"] == "invoke"]
    assert len(invokes) == 300
    # every invoke has a completion
    assert len(hist) == 600
    # concurrency actually happened: some op overlaps another
    a = analysis_host(models.cas_register(0), hist)
    assert a["valid?"] is True


def test_histories_are_time_ordered_and_indexed():
    r = random.Random(7)
    test = testkit.noop_test()
    test.update({
        "concurrency": 3,
        "client": testkit.atom_client(testkit.AtomState(0)),
        "generator": gen.clients(gen.limit(30, cas_mix(r))),
    })
    hist = run_test(test)
    ts = [o["time"] for o in hist]
    assert ts == sorted(ts)
    procs = {o["process"] for o in hist}
    assert procs <= {0, 1, 2}


class CrashyClient(jclient.Client):
    """Crashes every third invoke; tracks open/close balance."""

    def __init__(self):
        self.n = 0
        self.opens = 0
        self.closes = 0

    def open(self, test, node):
        self.opens += 1
        return self

    def close(self, test):
        self.closes += 1

    def invoke(self, test, op):
        self.n += 1
        if self.n % 3 == 0:
            raise RuntimeError("kaboom")
        out = dict(op)
        out["type"] = "ok"
        return out


def test_worker_crash_becomes_info_and_process_retires():
    test = testkit.noop_test()
    client = CrashyClient()
    test.update({
        "concurrency": 2,
        "client": client,
        "generator": gen.clients(
            gen.limit(12, gen.repeat({"f": "read"}))),
    })
    hist = run_test(test)
    infos = [o for o in hist if o["type"] == "info"]
    assert infos, "crashes must surface as info ops"
    for o in infos:
        assert o["error"].startswith("indeterminate")
    # crashed processes are retired: fresh process ids appear
    assert max(o["process"] for o in hist) >= 2
    # a non-reusable client is closed+reopened for each fresh process
    assert client.opens > 1
    assert client.closes >= client.opens - 1


class FailingOpen(jclient.Client):
    def open(self, test, node):
        raise RuntimeError("cannot connect")

    def invoke(self, test, op):
        raise AssertionError("unreachable")


def test_failed_open_yields_fail_ops_not_hang():
    test = testkit.noop_test()
    test.update({
        "concurrency": 2,
        "client": FailingOpen(),
        "generator": gen.clients(
            gen.limit(4, gen.repeat({"f": "read"}))),
    })
    hist = run_test(test)
    fails = [o for o in hist if o["type"] == "fail"]
    assert len(fails) == 4
    assert all(o["error"][0] == "no-client" for o in fails)


def test_nemesis_ops_route_to_nemesis():
    seen = []

    def nem(test, op):
        seen.append(op["f"])
        out = dict(op)
        out["value"] = "partitioned"
        return out

    from jepsen_tpu import nemesis as jnemesis
    test = testkit.noop_test()
    test.update({
        "concurrency": 2,
        "client": testkit.atom_client(testkit.AtomState(0)),
        "nemesis": jnemesis.FnNemesis(nem),
        "generator": gen.phases(
            gen.nemesis(gen.once({"type": "info", "f": "start"})),
            gen.clients(gen.limit(4, gen.repeat({"f": "read"}))),
        ),
    })
    hist = run_test(test)
    assert seen == ["start"]
    nem_ops = [o for o in hist if o["process"] == "nemesis"]
    assert len(nem_ops) == 2  # invoke + completion
    assert nem_ops[-1]["value"] == "partitioned"


def test_sleep_and_log_ops_stay_out_of_history():
    test = testkit.noop_test()
    test.update({
        "concurrency": 1,
        "client": testkit.atom_client(testkit.AtomState(0)),
        "generator": gen.clients([
            gen.once(gen.sleep(0.01)),
            gen.once(gen.log("hello")),
            gen.once({"f": "read"}),
        ]),
    })
    hist = run_test(test)
    assert all(o.get("type") not in ("sleep", "log") for o in hist)
    assert [o["f"] for o in hist] == ["read", "read"]


def test_generator_exception_shuts_down_workers():
    def boom():
        raise RuntimeError("generator exploded")

    test = testkit.noop_test()
    test.update({
        "concurrency": 2,
        "client": testkit.atom_client(testkit.AtomState(0)),
        "generator": gen.clients([gen.once({"f": "read"}), boom]),
    })
    with pytest.raises(gen.GenException):
        run_test(test)


def test_time_limited_run_terminates():
    r = random.Random(3)
    test = testkit.noop_test()
    test.update({
        "concurrency": 3,
        "client": testkit.atom_client(testkit.AtomState(0),
                                      latency_s=0.0002),
        "generator": gen.clients(
            gen.time_limit(0.3, gen.stagger(0.001, cas_mix(r)))),
    })
    hist = run_test(test)
    assert len(hist) > 10
    assert History(hist).pair_index()  # well-formed pairs
