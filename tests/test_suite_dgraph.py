"""Dgraph suite tests: the HTTP txn client against the in-process SI
fake, conflict/error classification, checker units, tracing-span
capture, and hermetic end-to-end runs for every workload."""

import pytest

from fake_dgraph import ABORTED_MSG, FakeDgraph

import jepsen_tpu.db as jdb
import jepsen_tpu.os_ as jos
from jepsen_tpu import core, trace
from jepsen_tpu.suites import dgraph as dg
from jepsen_tpu.suites.dgraph import (DgraphConn, DgraphError, Txn,
                                      alter_schema, txn, upsert,
                                      with_conflict_as_fail)


@pytest.fixture
def fake():
    f = FakeDgraph()
    yield f
    f.stop()


def conn_fn(fake):
    return lambda node: DgraphConn("127.0.0.1", fake.port, timeout_s=5.0)


# -- wire client -------------------------------------------------------------

def test_txn_roundtrip(fake):
    c = DgraphConn("127.0.0.1", fake.port)
    alter_schema(c, "key: int @index(int) .", "value: int .")
    with txn(c) as t:
        uids = t.mutate({"key": 1, "value": 10})
        assert uids
    with txn(c) as t:
        rows = t.query("{ q(func: eq(key, $key)) { uid value } }",
                       {"key": 1}).get("q")
        assert rows and rows[0]["value"] == 10
    c.close()


def test_snapshot_isolation(fake):
    """A txn reads at its start-ts: concurrent commits are invisible."""
    c1 = DgraphConn("127.0.0.1", fake.port)
    c2 = DgraphConn("127.0.0.1", fake.port)
    alter_schema(c1, "key: int @index(int) .", "value: int .")
    with txn(c1) as t:
        t.mutate({"key": 5, "value": 1})
    t1 = Txn(c1)
    r1 = t1.query("{ q(func: eq(key, $key)) { uid value } }", {"key": 5})
    assert r1["q"][0]["value"] == 1
    # another txn commits an update
    with txn(c2) as t2:
        rows = t2.query("{ q(func: eq(key, $key)) { uid } }",
                        {"key": 5})["q"]
        t2.mutate({"uid": rows[0]["uid"], "value": 2})
    # t1 still sees its snapshot
    r1b = t1.query("{ q(func: eq(key, $key)) { uid value } }",
                   {"key": 5})
    assert r1b["q"][0]["value"] == 1
    t1.discard()
    c1.close()
    c2.close()


def test_write_write_conflict_aborts(fake):
    c = DgraphConn("127.0.0.1", fake.port)
    alter_schema(c, "key: int @index(int) .", "value: int .")
    with txn(c) as t:
        t.mutate({"key": 9, "value": 0})
    with txn(c) as t:
        uid = t.query("{ q(func: eq(key, $key)) { uid } }",
                      {"key": 9})["q"][0]["uid"]
    ta, tb = Txn(c), Txn(c)
    ta.query("{ q(func: eq(key, $key)) { uid } }", {"key": 9})
    tb.query("{ q(func: eq(key, $key)) { uid } }", {"key": 9})
    ta.mutate({"uid": uid, "value": 1})
    tb.mutate({"uid": uid, "value": 2})
    ta.commit()
    with pytest.raises(DgraphError) as ei:
        tb.commit()
    assert ABORTED_MSG in ei.value.message
    c.close()


def test_upsert_index_conflict(fake):
    """@upsert predicates conflict on index keys: two blind inserts of
    the same value race, one must abort."""
    c = DgraphConn("127.0.0.1", fake.port)
    alter_schema(c, "email: string @index(exact) @upsert .")
    ta, tb = Txn(c), Txn(c)
    ta.query("{ q(func: eq(email, $email)) { uid } }", {"email": "x"})
    tb.query("{ q(func: eq(email, $email)) { uid } }", {"email": "x"})
    ta.mutate({"email": "x"})
    tb.mutate({"email": "x"})
    ta.commit()
    with pytest.raises(DgraphError):
        tb.commit()
    c.close()


def test_error_classification(fake):
    op = {"f": "read", "process": 0}
    fake.fail_hook = lambda p, b: \
        "Conflicts with pending transaction. Please abort." \
        if p == "/mutate" else None
    c = DgraphConn("127.0.0.1", fake.port)

    def body():
        with txn(c) as t:
            t.mutate({"value": 1})
        return {**op, "type": "ok"}
    r = with_conflict_as_fail(op, body,
                              {"dgraph-conn-retry-delay": 0.0})
    assert r == {**op, "type": "fail", "error": "conflict"}
    fake.fail_hook = lambda p, b: "DEADLINE_EXCEEDED: too slow" \
        if p == "/query" else None

    def body2():
        with txn(c) as t:
            t.query("{ q(func: eq(email, $e)) { uid } }", {"e": "y"})
        return {**op, "type": "ok"}
    r = with_conflict_as_fail(op, body2,
                              {"dgraph-conn-retry-delay": 0.0})
    assert r["type"] == "info" and "timeout" in r["error"]
    fake.fail_hook = None
    c.close()


def test_upsert_helper(fake):
    c = DgraphConn("127.0.0.1", fake.port)
    alter_schema(c, "email: string @index(exact) .")
    with txn(c) as t:
        assert upsert(t, "email", {"email": "a"})   # inserted
    with txn(c) as t:
        assert upsert(t, "email", {"email": "a"}) is None  # updated
    c.close()


# -- tracing ----------------------------------------------------------------

def test_spans_exported_to_store_dir(fake, tmp_path):
    done = _run(fake, tmp_path, "set", **{"tracing": True,
                                          "set-stagger": 0.005})
    assert done["results"]["valid?"] is True
    traces = tmp_path / "traces.jsonl"
    assert traces.exists(), "spans must land in the store dir"
    import json
    names = {json.loads(line)["operationName"]
             for line in traces.read_text().splitlines()}
    assert {"client.query", "client.mutate", "client.commit"} <= names
    # and the in-memory buffer agrees
    assert trace.tracer().spans("client.mutate")


def test_bank_annotates_checker_violations(fake, tmp_path):
    """A mid-run balance violation must tag the live span
    (`bank.clj:155-168`)."""
    trace.tracing(str(tmp_path / "t.jsonl"))
    c = DgraphConn("127.0.0.1", fake.port)
    client = dg.BankClient()
    client.conn = c
    test = {"accounts": [0, 1], "total-amount": 100,
            "dgraph-conn-retry-delay": 0.0}
    client.setup(test)
    # corrupt the bank: add 50 out of thin air
    with txn(c) as t:
        rows = t.query("{ q(func: eq(type_0, $type)) { uid amount_0 } }",
                       {"type": "account"}).get("q")
        t.mutate({"uid": rows[0]["uid"],
                  "amount_0": rows[0]["amount_0"] + 50})
    out = client.invoke(test, {"f": "read", "process": 0})
    assert out["error"] == "checker-violation"
    assert out["message"]["type"] == "wrong-total"
    assert out["message"]["trace-id"] is not None
    bad = [s for s in trace.tracer().spans()
           if s["tags"] and any(t["key"] == "checker_violation"
                                for t in s["tags"])]
    assert bad, "violation must be tagged on a span"
    trace.tracing(None)
    c.close()


# -- e2e runs ---------------------------------------------------------------

def _run(fake, tmp_path, workload, time_limit=3, nemesis=(), **opts):
    t = dg.dgraph_test({
        "nodes": ["n1", "n2", "n3"], "concurrency": 6,
        "ssh": {"dummy": True}, "workload": workload,
        "rate": 200, "time-limit": time_limit,
        "nemesis": list(nemesis),
        "store-dir": str(tmp_path),
        "dgraph-conn-fn": conn_fn(fake),
        "dgraph-conn-retry-delay": 0.0,
        **opts})
    t["db"] = jdb.noop
    t["os"] = jos.noop
    return core.run(t)


def test_e2e_bank(fake, tmp_path):
    """upsert-schema makes account creation conflict on index keys —
    without it, concurrent transfers can create duplicate accounts
    (the real dgraph anomaly this workload exists to catch)."""
    done = _run(fake, tmp_path, "bank", **{"upsert-schema": True})
    assert done["results"]["valid?"] is True
    reads = [o for o in done["history"]
             if o.get("f") == "read" and o.get("type") == "ok"]
    assert reads and all(
        sum(v for v in r["value"].values() if v) == 100 for r in reads)


def test_e2e_upsert(fake, tmp_path):
    done = _run(fake, tmp_path, "upsert", **{"upsert-schema": True})
    assert done["results"]["valid?"] is True
    wl = done["results"]["workload"]
    assert wl["valid?"] is True


def test_e2e_delete(fake, tmp_path):
    # @upsert: two concurrent upserts of the same key must conflict,
    # else duplicate records are expected under SI
    done = _run(fake, tmp_path, "delete",
                **{"delete-stagger": 0.005, "ops-per-key": 50,
                   "upsert-schema": True})
    assert done["results"]["valid?"] is True


def test_e2e_set(fake, tmp_path):
    done = _run(fake, tmp_path, "set", **{"set-stagger": 0.005})
    assert done["results"]["valid?"] is True


def test_e2e_uid_set(fake, tmp_path):
    done = _run(fake, tmp_path, "uid-set", **{"set-stagger": 0.005})
    assert done["results"]["valid?"] is True


def test_e2e_sequential(fake, tmp_path):
    done = _run(fake, tmp_path, "sequential")
    assert done["results"]["valid?"] is True


def test_e2e_linearizable_register(fake, tmp_path):
    done = _run(fake, tmp_path, "linearizable-register",
                **{"per-key-limit": 40})
    assert done["results"]["valid?"] is True


def test_e2e_uid_linearizable_register(fake, tmp_path):
    done = _run(fake, tmp_path, "uid-linearizable-register",
                **{"per-key-limit": 40})
    assert done["results"]["valid?"] is True


def test_e2e_long_fork(fake, tmp_path):
    done = _run(fake, tmp_path, "long-fork")
    assert done["results"]["valid?"] is True


def test_e2e_wr(fake, tmp_path):
    done = _run(fake, tmp_path, "wr")
    assert done["results"]["valid?"] is True
    txns = [o for o in done["history"]
            if o.get("f") == "txn" and o.get("type") == "ok"]
    assert txns, "wr run must land transactions"


def test_e2e_with_tablet_mover(fake, tmp_path):
    # wr's 10 striped predicates give the mover 10 tablets per
    # invocation, so "at least one actual move" is deterministic in
    # practice (the set workload's 2 tablets made this flaky)
    done = _run(fake, tmp_path, "wr", time_limit=4,
                nemesis=("move-tablet",),
                **{"nemesis-interval": 0.5,
                   "dgraph-zero-state-fn": lambda node: fake.state(),
                   "dgraph-move-tablet-fn":
                       lambda node, pred, group:
                           fake.moves.append((pred, group))})
    assert done["results"]["valid?"] is True
    moves = [o for o in done["history"] if o.get("f") == "move-tablet"]
    assert moves, "tablet mover must act"
    assert fake.moves, "tablet moves must reach zero"


def test_workload_menu_registered():
    from jepsen_tpu.suites import suite
    mod = suite("dgraph")
    assert set(mod.WORKLOADS) == {
        "bank", "upsert", "delete", "set", "uid-set", "sequential",
        "linearizable-register", "uid-linearizable-register",
        "long-fork", "wr", "types"}
    assert "types" not in mod.STANDARD_WORKLOADS


def test_nemesis_fault_stream_recurs():
    """Fault schedules must repeat for the whole run, not fire once
    (bare op dicts are one-shot generators)."""
    from jepsen_tpu import generator as g

    pkg = dg.dgraph_nemesis_package({"kill-alpha": True,
                                     "interval": 0.001})
    ctx = g.context({"concurrency": 2})
    stream = pkg["generator"]
    fs = []
    for _ in range(8):
        res = g.op(stream, {"nodes": ["n1"]}, ctx)
        assert res is not None, "nemesis stream exhausted"
        o, stream = res
        if o is g.PENDING:
            continue
        fs.append(o["f"])
        ctx = g.Context(ctx.time + 10_000_000, ctx.free_threads,
                        ctx.workers)
    assert fs.count("stop-alpha") >= 2, fs
    assert fs.count("start-alpha") >= 2, fs


def test_e2e_types_exact(fake, tmp_path):
    """A store with exact integers passes the type-safety probe."""
    done = _run(fake, tmp_path, "types", time_limit=6,
                **{"type-cases": 40, "types-stagger": 0.002,
                   "types-settle": 0.2})
    w = done["results"]["workload"]
    assert w["valid?"] in (True, "unknown"), w
    assert w["error-count"] == 0, w


def test_e2e_types_catches_float_coercion(tmp_path):
    """A store that round-trips integers through float64 (real
    dgraph's JSON path) must be flagged: values past 2^53 corrupt."""
    f = FakeDgraph(float_coerce=True)
    try:
        done = _run(f, tmp_path, "types", time_limit=6,
                    **{"type-cases": 60, "types-stagger": 0.002,
                       "types-settle": 0.2})
        w = done["results"]["workload"]
        assert w["valid?"] is False and w["error-count"] > 0, w
        bad = w["errors"][0]
        assert bad["wrote"] != bad["read"]
    finally:
        f.stop()


def test_merged_windows():
    """`sequential.clj:139-158` window merging."""
    assert dg.merged_windows(2, []) == []
    assert dg.merged_windows(2, [5]) == [[3, 7]]
    assert dg.merged_windows(2, [5, 6]) == [[3, 8]]
    assert dg.merged_windows(2, [5, 20]) == [[3, 7], [18, 22]]


def test_sequential_plotter_writes_svg(tmp_path):
    """Non-monotonic spots produce windowed SVG plots in the store."""
    hist = []
    for i, v in enumerate([1, 2, 3, 1, 4]):   # dip at index 3
        hist.append({"type": "ok", "f": "read", "process": 0,
                     "value": v, "time": i * 10**9})
    test = {"name": "seqplot", "start-time": "t0",
            "store-dir": str(tmp_path)}
    res = dg.SequentialPlotter().check(test, hist, {})
    assert res["valid?"] is True
    svgs = list((tmp_path / "seqplot" / "t0").glob("sequential-*.svg"))
    assert svgs, "plot must be written"
    assert "register value" in svgs[0].read_text()


def test_all_tests_sweep_builds():
    """The test-all sweep builds every standard workload x nemesis
    combo without errors, excluding types (`core.clj:215-231`)."""
    tests = list(dg._all_tests({
        "nodes": ["n1", "n2", "n3"], "concurrency": 6,
        "ssh": {"dummy": True}, "time-limit": 1}))
    assert len(tests) == (len(dg.STANDARD_NEMESES)
                          * len(dg.STANDARD_WORKLOADS))
    names = {t["name"] for t in tests}
    assert "dgraph bank" in names
    assert all("types" not in n for n in names)
