"""Pallas hash-dedup kernel (checker/wgl_dedup.py): kernel-level
exactness, interpret-mode parity with the XLA sort path across the
offline and streaming entries, and the engine cost-model autoselect.

The parity matrix pins the module contract: on shapes where the sort
path does not overflow, the hash-dedup kernel family produces the same
summaries (valid?, max-frontier) and the same blame certificates
(op-index) — offline, batched, mesh-sharded, and through
`check_stream_chunk`.
Shapes are kept small and shared (tier-1 budget); the broader sweep is
marked slow.
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu import models
from jepsen_tpu.checker import streaming, synth, wgl, wgl_dedup
from jepsen_tpu.history import History

MODEL = models.cas_register()

# one sort-family shape shared by every device test here: F=256, P=16
FRONTIER = 256
SLOTS = 16


def _hist(n=120, conc=4, seed=0, crash=0.02):
    return synth.register_history(n, concurrency=conc, values=4,
                                  crash_rate=crash, seed=seed)


def _corrupt_packed(h, seed=0):
    """synth.corrupt, but with a small out-of-domain value (9 instead
    of 10**6) so the state range stays narrow enough to pack — the
    corrupted run must exercise the HASH dedup's blame path, not fall
    back to the multi-word sort."""
    import random
    rng = random.Random(seed)
    ops = [dict(o) for o in h.ops]
    reads = [i for i, o in enumerate(ops)
             if o["type"] == "ok" and o["f"] == "read"]
    ops[rng.choice(reads)]["value"] = 9
    return History(ops)


def _run(h, pallas, **kw):
    return wgl.analysis_tpu(MODEL, h, frontier=FRONTIER, slots=SLOTS,
                            engine="sort", pallas=pallas, **kw)


# -- kernel-level exactness -------------------------------------------------

def test_kernel_dedup_first_seen_order_and_new_flags():
    N, F = 64, 16
    fn = wgl_dedup.dedup_fn(N, F, interpret=True)
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 12, N).astype(np.int32)
    keys[rng.random(N) < 0.25] = wgl_dedup.EMPTY
    out, new, cnt, dig = map(np.asarray, fn(keys))
    # reference: first-seen order over valid keys
    seen: dict = {}
    for i, k in enumerate(keys.tolist()):
        if k >= 0 and k not in seen:
            seen[k] = i >= F
    want = list(seen.items())
    assert out[:len(want)].tolist() == [k for k, _ in want]
    assert new[:len(want)].tolist() == [n for _, n in want]
    assert int(cnt) == len(want)
    assert (out[len(want):] == wgl_dedup.EMPTY).all()
    assert not new[len(want):].any()
    # the table-occupancy XOR digest matches a host recompute over the
    # distinct keys — the cross-check wgl.dedup_hash folds into att
    exp = 0
    for k, _ in want:
        exp ^= k
    exp ^= (len(want) * wgl_dedup.DIGEST_COUNT_MIX) & 0xFFFFFFFF
    exp &= 0xFFFFFFFF
    if exp >= 1 << 31:
        exp -= 1 << 32
    assert int(dig) == exp


def test_kernel_dedup_overflow_counts_all_distinct():
    N, F = 64, 8
    fn = wgl_dedup.dedup_fn(N, F, interpret=True)
    keys = np.arange(N, dtype=np.int32)          # all distinct
    out, new, cnt, _dig = map(np.asarray, fn(keys))
    assert int(cnt) == N                         # > F: overflow signal
    assert out.tolist() == list(range(F))        # first F kept
    assert (~new[:F]).sum() == F                 # all old-segment rows


def test_kernel_dedup_all_empty():
    fn = wgl_dedup.dedup_fn(32, 8, interpret=True)
    out, new, cnt, dig = map(np.asarray, fn(np.full(32, -1, np.int32)))
    assert int(cnt) == 0 and (out == wgl_dedup.EMPTY).all()
    assert int(dig) == 0


def test_eligibility_bounds():
    assert wgl_dedup.eligible(256, 16)
    assert wgl_dedup.eligible(1024, 16)
    # F=65536 x P=32: ~2.1M keys + 8.4M-slot table blow the VMEM gate
    assert not wgl_dedup.eligible(65536, 32)
    # capacity accounting: keys + table + 2 output buffers
    n = 1024 * 17
    assert wgl_dedup.table_size(n) == 2 * 32768


# -- interpret-mode parity matrix vs the sort path --------------------------

@pytest.mark.parametrize("seed", [0, 1])
def test_parity_valid_histories(seed):
    h = _hist(seed=seed)
    a = _run(h, pallas=False)
    b = _run(h, pallas=True)
    assert a["dedup"] == wgl.DEDUP_SORT
    assert b["dedup"] == wgl.DEDUP_PALLAS
    assert a["valid?"] is b["valid?"] is True
    # no overflow on this shape: frontiers are set-equal, so the peak
    # count matches exactly
    assert a["max-frontier"] == b["max-frontier"]


@pytest.mark.parametrize("seed", [0, 1])
def test_parity_blame_identity(seed):
    bad = _corrupt_packed(_hist(seed=seed), seed=seed)
    a = _run(bad, pallas=False)
    b = _run(bad, pallas=True)
    assert b["dedup"] == wgl.DEDUP_PALLAS   # still packed
    assert a["valid?"] is b["valid?"] is False
    assert a.get("op-index") == b.get("op-index")
    assert a.get("op") == b.get("op")


def test_parity_mutex_model():
    ops = []
    for i in range(12):
        p = i % 3
        ops += [{"type": "invoke", "f": "acquire", "value": None,
                 "process": p, "time": 2 * i},
                {"type": "ok", "f": "acquire", "value": None,
                 "process": p, "time": 2 * i + 1},
                {"type": "invoke", "f": "release", "value": None,
                 "process": p, "time": 2 * i + 1},
                {"type": "ok", "f": "release", "value": None,
                 "process": p, "time": 2 * i + 2}]
    h = History(ops)
    a = wgl.analysis_tpu(models.mutex(), h, frontier=FRONTIER,
                         slots=SLOTS, engine="sort", pallas=False)
    b = wgl.analysis_tpu(models.mutex(), h, frontier=FRONTIER,
                         slots=SLOTS, engine="sort", pallas=True)
    assert a["valid?"] is b["valid?"] is True
    assert a["max-frontier"] == b["max-frontier"]


def test_unpacked_shapes_keep_the_sort():
    """Wide masks (P=64 -> W=2) have no packed key: pallas=True must
    transparently keep the lexicographic sort, same verdict."""
    h = _hist(seed=2)
    a = wgl.analysis_tpu(MODEL, h, frontier=FRONTIER, slots=64,
                         engine="sort", pallas=True)
    assert a["dedup"] == wgl.DEDUP_SORT
    assert a["valid?"] is True


def test_hash_dedup_tighter_under_frontier_pressure():
    """The documented divergence: sorted duplicate runs make the sort
    path overflow conservatively; the hash path only overflows when
    the distinct count itself exceeds F — so at a tight frontier the
    hash path may keep MORE configs, never fewer, and 'valid' verdicts
    agree."""
    h = synth.register_history(120, concurrency=5, values=4,
                               crash_rate=0.05, seed=7)
    a = _run(h, pallas=False)
    b = _run(h, pallas=True)
    assert a["valid?"] is b["valid?"] is True
    assert b["max-frontier"] >= a["max-frontier"]


def test_batch_parity():
    hs = [_hist(seed=s) for s in (0, 1)]
    hs.append(_corrupt_packed(hs[0], seed=0))
    a = wgl.analysis_tpu_batch(MODEL, hs, frontier=FRONTIER,
                               slots=SLOTS, engine="sort", pallas=False)
    b = wgl.analysis_tpu_batch(MODEL, hs, frontier=FRONTIER,
                               slots=SLOTS, engine="sort", pallas=True)
    assert [r["valid?"] for r in a] == [r["valid?"] for r in b] \
        == [True, True, False]
    assert [r.get("op-index") for r in a] == \
        [r.get("op-index") for r in b]
    assert b[0]["dedup"] == wgl.DEDUP_PALLAS


def test_sharded_parity_and_group_info():
    """check_batch_sharded threads the same knobs: dedup on/off agrees
    per key, and return_info reports which family/dedup each dispatch
    group ran (the bench config-4 artifact). Same (F, P) shape as the
    rest of the module so the kernels are shared."""
    hs = [_hist(seed=s) for s in (0, 1)] + \
        [_corrupt_packed(_hist(seed=0), seed=0)]
    kw = dict(frontier=FRONTIER, slots=SLOTS, engine="sort")
    all_a, per_a = wgl.check_batch_sharded(MODEL, hs, pallas=False,
                                           **kw)
    all_b, per_b, info = wgl.check_batch_sharded(
        MODEL, hs, pallas=True, return_info=True, **kw)
    assert all_a is all_b is False
    assert per_a.tolist() == per_b.tolist() == [True, True, False]
    assert info["groups"] and all(
        g["family"] == "sort" and g["dedup"] == wgl.DEDUP_PALLAS
        for g in info["groups"])
    assert sum(g["keys"] for g in info["groups"]) == len(hs)


# -- streaming entry (check_stream_chunk) -----------------------------------

def test_stream_chunk_resume_verdict_and_blame_identity():
    """A declared state range packs the online sort stream; dedup
    on/off must produce identical streamed verdicts and blame across
    chunk boundaries."""
    h = synth.register_history(300, concurrency=4, values=4,
                               crash_rate=0.02, seed=11)
    kw = dict(chunk_entries=128, slots=8, state_range=(-1, 3))
    r_on = streaming.stream_check(MODEL, h, pallas=True, **kw)
    r_off = streaming.stream_check(MODEL, h, pallas=False, **kw)
    assert r_on["dedup"] == wgl.DEDUP_PALLAS
    assert r_off["dedup"] == wgl.DEDUP_SORT
    assert r_on["valid?"] is r_off["valid?"] is True
    assert r_on["chunks"] == r_off["chunks"] > 1

    # the corrupt value (9) stays inside a wider declared range, so
    # the packed stream never range-escapes and blame stays on-device
    bad = _corrupt_packed(h, seed=4)
    kw_bad = dict(chunk_entries=128, slots=8, state_range=(-1, 9))
    b_on = streaming.stream_check(MODEL, bad, pallas=True, **kw_bad)
    b_off = streaming.stream_check(MODEL, bad, pallas=False, **kw_bad)
    assert b_on["dedup"] == wgl.DEDUP_PALLAS
    assert b_on["valid?"] is b_off["valid?"] is False
    assert b_on.get("op-index") == b_off.get("op-index")


def test_stream_range_escape_downgrades_packed_sort():
    """Values outside the declared range must drop the packed key (and
    its hash dedup) and replay on the unpacked sort kernel — verdict
    preserved."""
    h = synth.register_history(80, concurrency=4, values=6,
                               crash_rate=0.0, seed=5)
    r = streaming.stream_check(MODEL, h, chunk_entries=64, slots=8,
                               state_range=(-1, 2), pallas=True)
    assert r["valid?"] is True
    assert r["dedup"] == wgl.DEDUP_SORT


# -- engine autoselect (cost model) -----------------------------------------

def test_select_engine_prefers_dense_on_small_tables():
    d = wgl.select_engine((-1, 4), 8, 1000)
    assert d.family == "dense" and d.dense is not None
    assert d.dedup == wgl.DEDUP_NONE


def test_select_engine_routes_big_tables_to_sort():
    # S=512 x 2^13 fits the dense caps but its modeled closure work
    # dwarfs the sort family's — the cost model must route it away
    d = wgl.select_engine((0, 400), 13, 10_000)
    assert d.family == "sort"
    assert "dense" in d.reason


def test_select_engine_dense_slot_cap():
    d = wgl.select_engine((-1, 4), 8, 1000, dense_slot_cap=6)
    assert d.family == "sort" and "dense_slot_cap" in d.reason
    with pytest.raises(ValueError):
        wgl.select_engine((-1, 4), 8, 1000, engine="dense",
                          dense_slot_cap=6)


def test_select_engine_forced_families():
    assert wgl.select_engine((-1, 4), 8, 100,
                             engine="dense").family == "dense"
    assert wgl.select_engine((-1, 4), 8, 100,
                             engine="sort").family == "sort"
    with pytest.raises(ValueError):
        wgl.select_engine((-1, 4), 8, 100, engine="nope")
    # forced dense past the caps still raises (offline contract)
    with pytest.raises(ValueError):
        wgl.select_engine((0, 10 ** 6), 8, 100, engine="dense")


def test_checker_options_flow_through_linearizable():
    """Linearizable(engine=..., dense_slot_cap=..., pallas=...) — the
    doc/plan.md 'Checkers' graduation — reaches the device engine."""
    from jepsen_tpu.checker.linear import Linearizable

    h = _hist(n=60, seed=3)
    c = Linearizable(MODEL, engine="sort", frontier=FRONTIER,
                     slots=SLOTS, pallas=True)
    r = c.check({}, h, {})
    assert r["valid?"] is True and r["dedup"] == wgl.DEDUP_PALLAS
    c2 = Linearizable(MODEL, dense_slot_cap=2)
    r2 = c2.check({}, h, {})
    assert r2["valid?"] is True and r2["analyzer"] == "tpu-wgl"


def test_env_gate_flips_next_call(monkeypatch):
    """JEPSEN_TPU_PALLAS_DEDUP resolves outside the kernel cache — the
    wgl_pallas closure contract, applied to the dedup gate."""
    h = _hist(n=60, seed=4)
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_DEDUP", "1")
    a = _run(h, pallas=None)
    assert a["dedup"] == wgl.DEDUP_PALLAS
    monkeypatch.setenv("JEPSEN_TPU_PALLAS_DEDUP", "0")
    b = _run(h, pallas=None)
    assert b["dedup"] == wgl.DEDUP_SORT
    assert a["valid?"] is b["valid?"] is True


def test_tpu_compile_probe_gates_hash_dedup(monkeypatch):
    """On a real TPU a failed one-time Mosaic compile probe downgrades
    the hash dedup to the sort path instead of raising out of the
    checker mid-run; interpret mode (off-TPU) never consults it."""
    pack = wgl._pack_params((-1, 3), SLOTS)
    assert pack is not None
    monkeypatch.setattr(wgl_dedup, "_PROBE", False)
    assert not wgl._hash_gate(FRONTIER, SLOTS, pack, on_tpu=True)
    assert wgl._hash_gate(FRONTIER, SLOTS, pack, on_tpu=False)
    monkeypatch.setattr(wgl_dedup, "_PROBE", True)
    assert wgl._hash_gate(FRONTIER, SLOTS, pack, on_tpu=True)


def test_compile_probe_is_cached_and_never_raises(monkeypatch):
    monkeypatch.setattr(wgl_dedup, "_PROBE", None)
    r = wgl_dedup.compiles()
    assert isinstance(r, bool)
    assert wgl_dedup._PROBE is r           # resolved once per process


# -- broader sweep: excluded from tier-1 ------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("conc,crash", [(4, 0.02), (5, 0.03)])
def test_parity_sweep(seed, conc, crash):
    # same (FRONTIER, SLOTS) shape as the tier-1 matrix so the sweep
    # reuses its compiled kernels, and kept below the overflow regime:
    # interpret-mode pallas is serial per key, so an escalation (F x4
    # recompiles + 4x-wider serial dedup loops) would blow the CI
    # budget — high-pressure shapes are the hardware round's job
    h = synth.register_history(160, concurrency=conc, values=4,
                               crash_rate=crash, seed=100 + seed)
    for hist in (h, _corrupt_packed(h, seed=seed)):
        a = _run(hist, pallas=False)
        b = _run(hist, pallas=True)
        assert a["valid?"] == b["valid?"]
        assert a.get("op-index") == b.get("op-index")
