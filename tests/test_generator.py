"""Generator system tests, mirroring the reference's simulator-first test
strategy (`jepsen/test/jepsen/generator_test.clj`): deterministic
simulation with a pinned RNG, exact assertions on op streams."""

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.generator import simulate as sim


def fs(history):
    return [o.get("f") for o in history]


def times(history):
    return [o["time"] for o in history]


# -- lifting ----------------------------------------------------------------

def test_dict_is_one_shot_generator():
    h = sim.quick({"f": "write", "value": 1})
    assert len(h) == 1
    o = h[0]
    assert o["f"] == "write" and o["value"] == 1
    assert o["type"] == "invoke"
    assert o["time"] == 0
    assert o["process"] in (0, 1, "nemesis")


def test_fn_generator_is_called_repeatedly():
    n = {"count": 0}

    def g():
        n["count"] += 1
        if n["count"] <= 3:
            return {"f": "read"}
        return None

    h = sim.quick(g)
    assert fs(h) == ["read"] * 3


def test_fn_generator_with_test_ctx_arity():
    def g(test, ctx):
        return {"f": "read", "value": ctx.time}

    h = sim.quick(gen.limit(2, g))
    assert fs(h) == ["read", "read"]


def test_sequence_runs_elements_in_order():
    h = sim.quick([{"f": "a"}, {"f": "b"}, {"f": "c"}])
    assert fs(h) == ["a", "b", "c"]


def test_nested_sequences_flatten():
    h = sim.quick([[{"f": "a"}, {"f": "b"}], {"f": "c"}])
    assert fs(h) == ["a", "b", "c"]


def test_none_is_exhausted():
    assert sim.quick(None) == []


def test_none_inside_sequence_skipped():
    # None elements are exhausted generators; the sequence moves past them
    h = sim.quick([None, {"f": "a"}])
    assert fs(h) == ["a"]


# -- limit / once / repeat / cycle ------------------------------------------

def test_limit():
    h = sim.quick(gen.limit(3, lambda: {"f": "read"}))
    assert fs(h) == ["read"] * 3


def test_once():
    h = sim.quick(gen.once(lambda: {"f": "read"}))
    assert fs(h) == ["read"]


def test_repeat_of_one_shot_dict():
    h = sim.quick(gen.limit(4, gen.repeat({"f": "w"})))
    assert fs(h) == ["w"] * 4


def test_repeat_bounded():
    h = sim.quick(gen.repeat(2, {"f": "w"}))
    assert fs(h) == ["w", "w"]


def test_cycle_restarts_exhausted_generator():
    h = sim.quick(gen.cycle(3, [{"f": "a"}, {"f": "b"}]))
    assert fs(h) == ["a", "b"] * 3


# -- map / f-map / filter ----------------------------------------------------

def test_map_transforms_ops():
    def bump(o):
        o = dict(o)
        o["value"] = o["value"] + 1
        return o
    h = sim.quick(gen.map(bump, gen.limit(2, gen.repeat({"f": "w", "value": 1}))))
    assert [o["value"] for o in h] == [2, 2]


def test_f_map_renames_fs():
    h = sim.quick(gen.f_map({"start": "start-partition"},
                            gen.once({"f": "start"})))
    assert fs(h) == ["start-partition"]


def test_filter_drops_ops():
    seq = [{"f": "a", "value": i} for i in range(6)]
    h = sim.quick(gen.filter(lambda o: o["value"] % 2 == 0, seq))
    assert [o["value"] for o in h] == [0, 2, 4]


# -- thread routing ----------------------------------------------------------

def test_clients_excludes_nemesis():
    h = sim.quick(gen.clients(gen.limit(10, {"f": "r"})))
    assert all(o["process"] != "nemesis" for o in h)


def test_nemesis_only():
    h = sim.quick(gen.nemesis(gen.limit(4, {"f": "kill"})))
    assert all(o["process"] == "nemesis" for o in h)


def test_clients_nemesis_two_arity_routes_both():
    h = sim.quick(gen.clients(gen.limit(6, gen.repeat({"f": "r"})),
                              gen.limit(2, gen.repeat({"f": "kill"}))))
    cl = [o for o in h if o["process"] != "nemesis"]
    nm = [o for o in h if o["process"] == "nemesis"]
    assert fs(cl) == ["r"] * 6 and fs(nm) == ["kill"] * 2


def test_each_thread_gives_every_thread_a_copy():
    h = sim.quick(gen.each_thread({"f": "hi"}))
    # 2 workers + nemesis, one op each
    assert sorted(str(o["process"]) for o in h) == ["0", "1", "nemesis"]


def test_reserve_partitions_threads():
    ctx = sim.n_plus_nemesis_context(5)
    h = sim.quick(ctx, gen.clients(gen.reserve(
        2, gen.limit(10, gen.repeat({"f": "w"})),
        gen.limit(10, gen.repeat({"f": "r"})))))
    w_threads = {o["process"] for o in h if o["f"] == "w"}
    r_threads = {o["process"] for o in h if o["f"] == "r"}
    assert w_threads <= {0, 1}
    assert r_threads <= {2, 3, 4}
    assert len(h) == 20


# -- any / mix / flip-flop ---------------------------------------------------

def test_any_draws_from_all():
    h = sim.quick(gen.any(gen.limit(2, gen.repeat({"f": "a"})),
                          gen.limit(2, gen.repeat({"f": "b"}))))
    assert sorted(fs(h)) == ["a", "a", "b", "b"]


def test_mix_draws_from_all_and_exhausts():
    h = sim.quick(gen.mix([gen.limit(5, gen.repeat({"f": "a"})),
                           gen.limit(5, gen.repeat({"f": "b"}))]))
    assert sorted(fs(h)) == ["a"] * 5 + ["b"] * 5


def test_flip_flop_alternates():
    h = sim.quick(gen.flip_flop(gen.limit(3, gen.repeat({"f": "a"})),
                                gen.limit(5, gen.repeat({"f": "b"}))))
    assert fs(h) == ["a", "b", "a", "b", "a", "b"]


# -- timing ------------------------------------------------------------------

def test_stagger_spaces_ops_out():
    h = sim.perfect(gen.stagger(1, gen.limit(10, gen.repeat({"f": "r"}))))
    ts = times(h)
    assert ts == sorted(ts)
    assert ts[-1] > 0
    # mean spacing should be on the order of 1 s (2 s max per gap)
    gaps = [b - a for a, b in zip(ts, ts[1:])]
    assert all(0 <= g <= 2_000_000_000 for g in gaps)


def test_delay_spaces_exactly():
    h = sim.perfect(gen.delay(1, gen.limit(4, gen.repeat({"f": "r"}))))
    ts = times(h)
    assert ts == [0, 1_000_000_000, 2_000_000_000, 3_000_000_000]


def test_time_limit_cuts_off():
    h = sim.perfect(gen.time_limit(2, gen.delay(1, gen.repeat({"f": "r"}))))
    ts = times(h)
    assert ts == [0, 1_000_000_000]


def test_sleep_op_stays_out_of_history_but_advances_time():
    # interpreter parity: sleeps/logs are handled in the worker and
    # never reach the history (`interpreter.py:117,141-144`)
    assert sim.quick_ops(gen.once(gen.sleep(2))) == []
    h = sim.quick_ops(gen.phases(gen.once(gen.sleep(2)),
                                 gen.once({"f": "read"})))
    assert [o["f"] for o in h] == ["read", "read"]
    assert h[0]["time"] >= 2_000_000_000


# -- phasing -----------------------------------------------------------------

def test_phases_run_in_order():
    h = sim.perfect(gen.phases(gen.limit(3, gen.repeat({"f": "a"})),
                               gen.limit(3, gen.repeat({"f": "b"}))))
    assert fs(h) == ["a"] * 3 + ["b"] * 3


def test_then_runs_b_first():
    h = sim.perfect(gen.then(gen.once({"f": "after"}),
                             gen.limit(2, gen.repeat({"f": "before"}))))
    assert fs(h) == ["before", "before", "after"]


def test_synchronize_waits_for_all_threads():
    # With perfect latency, ops overlap; synchronize must still order
    # phase b strictly after all of a's completions.
    full = sim.perfect_star(gen.phases(gen.limit(4, gen.repeat({"f": "a"})),
                                       gen.once({"f": "b"})))
    b_invoke = next(o for o in full
                    if o["f"] == "b" and o["type"] == "invoke")
    a_completions = [o for o in full
                     if o["f"] == "a" and o["type"] == "ok"]
    assert all(o["time"] <= b_invoke["time"] for o in a_completions)


# -- process limits and crash retirement -------------------------------------

def test_perfect_info_retires_processes():
    h = sim.perfect_info(gen.clients(gen.limit(6, gen.repeat({"f": "r"}))))
    # every client op crashes; processes must be retired and replaced
    procs = [o["process"] for o in h]
    assert len(set(procs)) == 6  # all distinct: 0,1 then 2,3 then 4,5


def test_process_limit_bounds_distinct_processes():
    h = sim.perfect_info(
        gen.process_limit(4, gen.clients(gen.repeat({"f": "r"}))))
    procs = {o["process"] for o in h}
    assert len(procs) <= 4


# -- until-ok ----------------------------------------------------------------

def test_until_ok_stops_after_first_ok():
    # imperfect cycles fail -> info -> ok per thread
    h = sim.imperfect(gen.until_ok(gen.repeat({"f": "r"})))
    oks = [o for o in h if o["type"] == "ok"]
    assert len(oks) >= 1
    first_ok_t = oks[0]["time"]
    later_invokes = [o for o in h
                     if o["type"] == "invoke" and o["time"] > first_ok_t]
    assert later_invokes == []


# -- cycle-times -------------------------------------------------------------

def test_cycle_times_windows():
    h = sim.perfect(gen.time_limit(
        4, gen.cycle_times(1, gen.delay(0.25, gen.repeat({"f": "a"})),
                           1, gen.delay(0.25, gen.repeat({"f": "b"})))))
    for o in h:
        window = (o["time"] // 1_000_000_000) % 2
        assert o["f"] == ("a" if window == 0 else "b"), (o, window)


# -- validate ----------------------------------------------------------------

def test_validate_rejects_busy_process():
    class Bad(gen.Gen):
        def op(self, test, ctx):
            return {"type": "invoke", "process": 99, "time": 0,
                    "f": "x"}, None
    with pytest.raises(gen.InvalidOp):
        sim.quick(Bad())


def test_validate_rejects_bad_type():
    class Bad(gen.Gen):
        def op(self, test, ctx):
            o = gen.fill_in_op({"f": "x"}, ctx)
            o["type"] = "wat"
            return o, None
    with pytest.raises(gen.InvalidOp):
        sim.quick(Bad())


# -- determinism -------------------------------------------------------------

def test_simulation_is_deterministic():
    g = gen.stagger(0.1, gen.limit(30, gen.mix([{"f": "a"}, {"f": "b"}])))
    h1 = sim.perfect(g)
    h2 = sim.perfect(g)
    assert h1 == h2


def test_friendly_exceptions_wraps():
    def boom():
        raise RuntimeError("nope")
    with pytest.raises(gen.GenException):
        sim.quick(gen.friendly_exceptions(boom))


# -- Python iterators as generators (lazy-seq parity) ------------------------

def test_iterator_generator():
    """Python iterators lift to generators, like the reference's lazy
    seqs — including infinite streams."""
    import itertools
    it = ({"type": "invoke", "f": "add", "value": i}
          for i in itertools.count())
    h = sim.quick(gen.limit(5, it))
    assert [o["value"] for o in h] == [0, 1, 2, 3, 4]


def test_iterator_generator_finite():
    it = iter([{"type": "invoke", "f": "a", "value": None},
               {"type": "invoke", "f": "b", "value": None}])
    assert [o["f"] for o in sim.quick(it)] == ["a", "b"]


def test_iterator_of_subgenerators():
    """Iterator elements may themselves be generators."""
    it = iter([gen.limit(2, gen.repeat({"f": "x"})),
               gen.once({"f": "y"})])
    assert [o["f"] for o in sim.quick(it)] == ["x", "x", "y"]
