"""In-process fake of the RobustIRC HTTP bridge: session creation,
message post (NICK/USER/JOIN/TOPIC), and the message stream read —
enough for the suite's set workload. Replies over plain HTTP (the
suite's irc-url-fn points here)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeRobustIRC:
    def __init__(self):
        self.lock = threading.Lock()
        self.sessions: dict[str, dict] = {}
        self.messages: list[dict] = []   # network-wide ordered log
        self.next_id = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, obj, raw=None):
                body = raw if raw is not None \
                    else json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                with outer.lock:
                    if self.path.endswith("/session"):
                        outer.next_id += 1
                        sid = f"s{outer.next_id}"
                        outer.sessions[sid] = {"auth": f"a{sid}"}
                        self._reply({"Sessionid": sid,
                                     "Sessionauth": f"a{sid}"})
                        return
                    sid = self.path.split("/")[3]
                    if sid not in outer.sessions:
                        self.send_response(404)
                        self.end_headers()
                        return
                    # the real network echoes messages with a sender
                    # prefix (":nick!user@host TOPIC #chan :v"), which
                    # is why clients parse the verb at position 1
                    outer.messages.append(
                        {"Data": f":{sid}!j@fake "
                                 f"{req.get('Data', '')}",
                         "Id": {"Id": len(outer.messages)}})
                    self._reply({})

            def do_GET(self):  # noqa: N802
                with outer.lock:
                    # concatenated JSON documents, like the real stream
                    body = "\n".join(
                        json.dumps(m) for m in outer.messages).encode()
                self._reply(None, raw=body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
