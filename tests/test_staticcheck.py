"""tools/staticcheck — the repo-specific static-analysis suite.

Fixture snippets per analyzer (positive AND negative per JTS code),
suppression + baseline handling, lock-order inversion, and the
self-check that the live jepsen_tpu/ tree is clean modulo the
committed baseline. Tier-0: pure AST work, no kernels."""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tools.staticcheck.base import SourceFile  # noqa: E402
from tools.staticcheck.devicesync import DeviceSyncAnalyzer  # noqa: E402
from tools.staticcheck.driver import (default_baseline, run,  # noqa: E402
                                      write_baseline)
from tools.staticcheck.lockcheck import LockAnalyzer  # noqa: E402
from tools.staticcheck.retrace import RetraceAnalyzer  # noqa: E402
from tools.staticcheck.style import StyleAnalyzer  # noqa: E402

CHECKER_REL = "jepsen_tpu/checker/fixture.py"


def codes(analyzer, rel, snippet):
    sf = SourceFile.from_text(rel, textwrap.dedent(snippet))
    assert analyzer.scope(sf), f"{rel} must be in {analyzer.name} scope"
    return [f.code for f in analyzer.check_file(sf)]


def findings(analyzer, rel, snippet):
    sf = SourceFile.from_text(rel, textwrap.dedent(snippet))
    return analyzer.check_file(sf)


# ---------------------------------------------------------------------------
# style (JTS00x)
# ---------------------------------------------------------------------------

def test_style_unused_and_duplicate_imports():
    got = codes(StyleAnalyzer(), "mod.py", """\
        import os
        import json
        import json
        print(json.dumps({}))
        """)
    assert got.count("JTS002") == 1   # os unused
    assert got.count("JTS003") == 1   # json twice


def test_style_string_annotation_names_count_as_used():
    # the old tools/lint.py false-positive class: typing-only names
    # referenced only from quoted annotations forced # noqa noise
    got = codes(StyleAnalyzer(), "mod.py", """\
        from typing import Optional, Sequence
        from collections import OrderedDict

        def f(x: "Optional[int]") -> "Sequence[OrderedDict]":
            return [x]
        """)
    assert "JTS002" not in got


def test_style_nested_forward_ref_in_real_annotation():
    got = codes(StyleAnalyzer(), "mod.py", """\
        from typing import Optional
        from collections import OrderedDict

        def f(x: Optional["OrderedDict"]) -> None:
            del x
        """)
    assert "JTS002" not in got


def test_style_whitespace_and_length():
    src = ("x = 1 \n"            # trailing whitespace
           "if x:\n"
           "\ty = 2\n"           # tab indent
           "z = '" + "a" * 120 + "'\n")
    got = [f.code for f in StyleAnalyzer().check_file(
        SourceFile.from_text("mod.py", src))]
    assert {"JTS004", "JTS005", "JTS006"} <= set(got)


def test_style_syntax_error():
    got = codes(StyleAnalyzer(), "mod.py", "def f(:\n")
    assert got == ["JTS001"]


# ---------------------------------------------------------------------------
# device-sync (JTS10x)
# ---------------------------------------------------------------------------

def test_jts101_raw_device_get():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        import jax

        def f(k, x):
            return jax.device_get(k.check(x))
        """)
    assert "JTS101" in got


def test_jts101_guarded_is_clean():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        from .._platform import guarded_device_get

        def f(k, x):
            return guarded_device_get(k.check(x), site="t")
        """)
    assert got == []


def test_jts102_block_until_ready():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        def f(y):
            return y.block_until_ready()
        """)
    assert got == ["JTS102"]


def test_jts103_asarray_over_entry_result():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        import numpy as np

        def f(k, x):
            carry = k.check_stream_chunk(x)
            return np.asarray(carry[0])
        """)
    assert got == ["JTS103"]


def test_jts103_int_over_factory_callable_result():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        def f(x):
            fn = _kernel("m", 1, 2, 3)
            out, cnt = fn(x)
            return int(cnt)
        """)
    assert got == ["JTS103"]


def test_jts103_guarded_fetch_then_host_math_is_clean():
    got = codes(DeviceSyncAnalyzer(), CHECKER_REL, """\
        import numpy as np
        from .._platform import guarded_device_get

        def f(k, x):
            carry = k.check_chunk(x)
            host = guarded_device_get(carry, site="t")
            return int(np.asarray(host[0]).sum())
        """)
    assert got == []


def test_devicesync_scope_is_checker_and_service():
    az = DeviceSyncAnalyzer()
    assert az.scope(SourceFile.from_text(CHECKER_REL, ""))
    assert az.scope(SourceFile.from_text("jepsen_tpu/service.py", ""))
    assert not az.scope(SourceFile.from_text("jepsen_tpu/core.py", ""))
    assert not az.scope(SourceFile.from_text("bench.py", ""))


# ---------------------------------------------------------------------------
# locks (JTS20x)
# ---------------------------------------------------------------------------

LOCK_MOD = """\
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0          # guarded-by: _lock

        def good(self):
            with self._lock:
                self.n += 1

        def bad(self):
            return self.n

        def held(self):  # holds: _lock
            return self.n
    """


def test_jts201_unguarded_access_and_exemptions():
    got = findings(LockAnalyzer(), "mod.py", LOCK_MOD)
    # one finding, in bad() — good()/held()/__init__ are exempt
    assert [f.code for f in got] == ["JTS201"]
    assert got[0].line == 13


def test_jts201_module_global():
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        _glock = threading.Lock()
        _state = 0   # guarded-by: _glock

        def bad():
            return _state

        def good():
            global _state
            with _glock:
                _state += 1
        """)
    assert got == ["JTS201"]


def test_jts201_module_global_in_guarded_class_reported_once():
    # telemetry.py's shape: module-level guarded globals AND a guarded
    # class; an unguarded module-global access inside a method of the
    # guarded class must yield ONE finding, not one per walk
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        GLOCK = threading.Lock()
        G = 0   # guarded-by: GLOCK

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0   # guarded-by: _lock

            def bad(self):
                global G
                G = 1
        """)
    assert got == ["JTS201"]


def test_jts202_lock_order_inversion():
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()
                self.x = 0   # guarded-by: a
                self.y = 0   # guarded-by: b

            def p(self):
                with self.a:
                    with self.b:
                        self.x, self.y = 1, 1

            def q(self):
                with self.b:
                    with self.a:
                        self.x, self.y = 2, 2
        """)
    assert got.count("JTS202") == 1


def test_jts202_consistent_order_is_clean():
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()
                self.x = 0   # guarded-by: a

            def p(self):
                with self.a:
                    with self.b:
                        self.x = 1

            def q(self):
                with self.a:
                    with self.b:
                        self.x = 2
        """)
    assert "JTS202" not in got


def test_jts203_unknown_lock():
    got = codes(LockAnalyzer(), "mod.py", """\
        class S:
            def __init__(self):
                self.n = 0   # guarded-by: _lock
        """)
    assert got == ["JTS203"]


def test_jts201_with_item_access_is_checked():
    # `with self._fh:` is an access to _fh, not a lock acquisition
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        class S:
            def __init__(self):
                self._io = threading.Lock()
                self._fh = open("x")   # guarded-by: _io

            def bad(self):
                with self._fh:
                    pass

            def good(self):
                with self._io:
                    with self._fh:
                        pass
        """)
    assert got == ["JTS201"]


def test_jts201_nested_function_reported_once():
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        _glock = threading.Lock()
        _g = 0   # guarded-by: _glock

        def outer():
            def inner():
                return _g
            return inner
        """)
    assert got == ["JTS201"]


def test_locks_inherited_annotation():
    got = codes(LockAnalyzer(), "mod.py", """\
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0.0   # guarded-by: _lock

        class Child(Base):
            def bad(self):
                return self.value

            def good(self):
                with self._lock:
                    return self.value
        """)
    assert got == ["JTS201"]


# ---------------------------------------------------------------------------
# retrace (JTS30x)
# ---------------------------------------------------------------------------

def test_jts301_jit_closure_over_mutable_global():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import jax

        _MODE = 0

        def set_mode(m):
            global _MODE
            _MODE = m

        @jax.jit
        def f(x):
            return x + _MODE
        """)
    assert got == ["JTS301"]


def test_jts301_single_assignment_constant_is_clean():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import jax
        import jax.numpy as jnp

        SCALE = 3

        @jax.jit
        def f(x):
            return x * jnp.int32(SCALE)
        """)
    assert got == []


def test_jts302_python_branch_on_traced_value():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """)
    assert got == ["JTS302"]


def test_jts302_static_properties_are_clean():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if x.dtype == jnp.uint32 and len(x.shape) > 1:
                return x.sum()
            return x
        """)
    assert got == []


def test_jts303_bare_scalar_at_kernel_entry():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        def f(k, x, sl):
            return k.check_stream_chunk(x, len(sl), 0)
        """)
    assert got.count("JTS303") == 2


def test_jts303_wrapped_scalar_is_clean():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import jax.numpy as jnp

        def f(k, x, sl):
            return k.check_stream_chunk(x, jnp.int32(len(sl)), x)
        """)
    assert got == []


def test_jts303_nested_function_reported_once():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        def outer(k, x):
            def inner():
                return k.check(x, 5, x)
            return inner
        """)
    assert got == ["JTS303"]


def test_jts304_unbucketed_batch_stack():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import numpy as np
        import jax.numpy as jnp

        def f(k, items, s):
            x = jnp.asarray(np.stack([i.x for i in items]))
            return k.check_batch(x, s, s)
        """)
    assert got == ["JTS304"]


def test_jts304_bucket_padded_stack_is_clean():
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import numpy as np
        import jax.numpy as jnp

        def f(k, items, s, E):
            padded = [i.pad_to(E) for i in items]
            padded += [Z] * (_bucket(len(padded), lo=1) - len(padded))
            x = jnp.asarray(np.stack([i.x for i in padded]))
            return k.check_batch(x, s, s)
        """)
    assert got == []


def test_jts304_sliced_stack_does_not_chain():
    # a sliced/re-chunked result no longer carries the stack's
    # dynamic length — the streaming recovery-replay shape
    got = codes(RetraceAnalyzer(), CHECKER_REL, """\
        import numpy as np

        def f(k, parts, need, s):
            tail = np.concatenate(parts)[-need:]
            carry = k.init_carry(s)
            return helper(tail, carry)
        """)
    assert got == []


# ---------------------------------------------------------------------------
# suppression, baseline, driver semantics
# ---------------------------------------------------------------------------

def _fixture_repo(tmp_path: Path, body: str) -> Path:
    d = tmp_path / "repo" / "jepsen_tpu" / "checker"
    d.mkdir(parents=True)
    (tmp_path / "repo" / "jepsen_tpu" / "__init__.py").write_text("")
    (d / "__init__.py").write_text("")
    (d / "mod.py").write_text(textwrap.dedent(body))
    return tmp_path / "repo"


BAD_SYNC = """\
    import jax

    def f(k, x):
        return jax.device_get(k.check(x))
    """


def test_driver_reports_seeded_violation(tmp_path):
    repo = _fixture_repo(tmp_path, BAD_SYNC)
    res = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
              baseline_path=tmp_path / "baseline.txt")
    assert res["findings"] == 1
    assert res["by_code"] == {"JTS101": 1}


def test_noqa_specific_code_suppresses(tmp_path):
    repo = _fixture_repo(tmp_path, """\
        import jax

        def f(k, x):
            return jax.device_get(k.check(x))  # noqa: JTS101 — why
        """)
    res = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
              baseline_path=tmp_path / "baseline.txt")
    assert res["findings"] == 0 and res["suppressed"] == 1


def test_noqa_wrong_code_does_not_suppress(tmp_path):
    repo = _fixture_repo(tmp_path, """\
        import jax

        def f(k, x):
            return jax.device_get(k.check(x))  # noqa: JTS999
        """)
    res = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
              baseline_path=tmp_path / "baseline.txt")
    assert res["findings"] == 1


def test_bare_noqa_suppresses(tmp_path):
    repo = _fixture_repo(tmp_path, """\
        import jax

        def f(k, x):
            return jax.device_get(k.check(x))  # noqa
        """)
    res = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
              baseline_path=tmp_path / "baseline.txt")
    assert res["findings"] == 0 and res["suppressed"] == 1


def test_baseline_roundtrip(tmp_path):
    repo = _fixture_repo(tmp_path, BAD_SYNC)
    bl = tmp_path / "baseline.txt"
    res = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
              baseline_path=bl)
    assert res["findings"] == 1
    write_baseline(bl, res["_all"])
    res2 = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
               baseline_path=bl)
    assert res2["findings"] == 0 and res2["baselined"] == 1
    # baseline entries carry no line numbers: adding a leading line
    # (shifting the finding) still matches
    mod = repo / "jepsen_tpu" / "checker" / "mod.py"
    mod.write_text("# moved\n" + mod.read_text())
    res3 = run(["jepsen_tpu"], only={"device-sync"}, repo=repo,
               baseline_path=bl)
    assert res3["findings"] == 0 and res3["baselined"] == 1


# ---------------------------------------------------------------------------
# CLI + live tree
# ---------------------------------------------------------------------------

def _cli(args, cwd=ROOT, timeout=240):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "tools.staticcheck", *args], cwd=cwd,
        env=env, capture_output=True, text=True, timeout=timeout)


def test_cli_seeded_fixture_exits_1(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nimport os\n")
    p = _cli([str(bad), "--only", "style",
              "--baseline", str(tmp_path / "b.txt")])
    assert p.returncode == 1
    assert "JTS002" in p.stdout and "JTS003" in p.stdout
    assert ":2: " in p.stdout   # path:line: CODE message shape


def test_cli_summary_json(tmp_path):
    p = _cli(["--only", "style,device-sync,locks,retrace",
              "--summary-json"])
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["findings"] == 0
    assert set(out["analyzers"]) == {"style", "device-sync", "locks",
                                     "retrace"}
    assert out["files"] > 100


def test_write_baseline_refuses_filtered_run(tmp_path):
    # a filtered run sees a subset of findings — writing it out would
    # erase baseline entries for the analyzers/files that did not run
    b = tmp_path / "b.txt"
    b.write_text("x.py: JTS201 pre-existing debt\n")
    for extra in (["--only", "style"], ["tools/staticcheck"]):
        p = _cli([*extra, "--write-baseline", "--baseline", str(b)])
        assert p.returncode == 2, p.stdout + p.stderr
        assert "requires a full run" in p.stderr
    assert b.read_text() == "x.py: JTS201 pre-existing debt\n"


def test_cli_subcommand_forwards_to_driver(tmp_path):
    """`jepsen-tpu staticcheck` (python -m jepsen_tpu staticcheck) is
    a thin forwarder to the driver: same flags, same exit codes."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nimport os\n")
    p = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "staticcheck", str(bad),
         "--only", "style", "--baseline", str(tmp_path / "b.txt")],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "JTS002" in p.stdout and "JTS003" in p.stdout
    p = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "staticcheck",
         "--only", "locks", "--summary-json"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["analyzers"] == ["locks"] and out["findings"] == 0


@pytest.mark.parametrize("shim", ["tools/lint.py",
                                  "tools/lint_metrics.py"])
def test_legacy_shims_still_pass(shim):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run([sys.executable, shim], cwd=ROOT, env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stdout + p.stderr


def test_live_tree_clean_modulo_baseline():
    """The self-check: the shipped jepsen_tpu/ tree has no unbaselined
    findings — the CI gate this PR installs."""
    res = run([], only={"style", "device-sync", "locks", "retrace"})
    live = [f.render() for f in res["_live"]]
    assert live == [], "\n".join(live)


def test_committed_baseline_matches_format():
    text = default_baseline().read_text()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        assert ": JTS" in line, line
